"""Perf harness: reference microbenchmark set + TPU compute benchmarks.

Reference: ``ray microbenchmark`` (``python/ray/_private/ray_perf.py:93``)
and the release perf logs reproduced in BASELINE.md. Prints ONE JSON line
(the headline metric) to stdout; the full result table goes to stderr and
``BENCH_DETAILS.json``.

Run on the real chip (no JAX_PLATFORMS override) for the TPU metrics;
runtime metrics run everywhere.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, Optional

# Baselines from BASELINE.md (reference release 2.22.0, m5.16xlarge 64 vCPU;
# this box is far smaller — vs_baseline is still the honest ratio).
BASELINES = {
    "tasks_sync_per_s": 971.0,
    "tasks_async_per_s": 8194.0,
    "actor_calls_sync_per_s": 2096.0,
    "actor_calls_async_per_s": 9063.0,
    "async_actor_calls_sync_per_s": 1326.0,
    "put_small_per_s": 5196.0,
    "get_small_per_s": 10270.0,
    "put_gbps": 20.1,
    "pg_create_remove_per_s": 838.0,
}


def _phase_trace(phase: str, fn: Callable[[], None]) -> None:
    """Run one bench phase and write its chrome-trace artifact
    (``BENCH_TRACE_<phase>.json``, next to BENCH_DETAILS.json): a perf
    regression in a trajectory ships WITH the timeline that explains it.
    The buffer is cleared per phase so each artifact is self-contained;
    the dump is best-effort (driver-side events always land — worker
    events only if a cluster is still connected at dump time)."""
    from ray_tpu.observability import timeline

    timeline.clear_events()
    try:
        fn()
    finally:
        try:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                f"BENCH_TRACE_{phase}.json",
            )
            timeline.dump_timeline(path)
            print(f"trace artifact: {path}", file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — artifacts never fail a bench
            print(f"trace artifact for {phase} failed: {e!r}", file=sys.stderr)


def _timeit(fn: Callable[[], int], min_time: float = 2.0) -> float:
    """Run fn (returns ops count) until min_time elapsed; return ops/s."""
    # warmup
    fn()
    total_ops = 0
    start = time.perf_counter()
    while time.perf_counter() - start < min_time:
        total_ops += fn()
    return total_ops / (time.perf_counter() - start)


def _percentiles(samples, fractions):
    xs = sorted(samples)
    out = []
    for f in fractions:
        idx = min(len(xs) - 1, max(0, round(f * (len(xs) - 1))))
        out.append(xs[idx])
    return out


def bench_runtime(results: Dict[str, Dict]) -> None:
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    @ray_tpu.remote
    class AsyncA:
        async def m(self):
            return None

    # warm the worker pool
    ray_tpu.get([noop.remote() for _ in range(20)], timeout=120)
    a = A.remote()
    aa = AsyncA.remote()
    ray_tpu.get(a.m.remote(), timeout=60)
    ray_tpu.get(aa.m.remote(), timeout=60)

    def tasks_sync():
        ray_tpu.get(noop.remote(), timeout=60)
        return 1

    def tasks_async():
        n = 200
        ray_tpu.get([noop.remote() for _ in range(n)], timeout=120)
        return n

    def actor_sync():
        ray_tpu.get(a.m.remote(), timeout=60)
        return 1

    def actor_async():
        n = 200
        ray_tpu.get([a.m.remote() for _ in range(n)], timeout=120)
        return n

    def async_actor_sync():
        ray_tpu.get(aa.m.remote(), timeout=60)
        return 1

    def put_small():
        n = 100
        for _ in range(n):
            ray_tpu.put(b"x" * 100)
        return n

    small_refs = [ray_tpu.put(b"y" * 100) for _ in range(100)]

    def get_small():
        for r in small_refs:
            ray_tpu.get(r, timeout=60)
        return len(small_refs)

    big = np.zeros(64 * 1024 * 1024, dtype=np.uint8)  # 64 MiB

    def put_big():
        ref = ray_tpu.put(big)
        ray_tpu.free(ref)
        return 1

    def put_big_gbps() -> float:
        """put_gbps, variance pinned (the 0.6→14.7 GB/s run-to-run swing):
        the old min-time loop sampled a DIFFERENT mix of cold page-fault
        puts vs warm pool-recycled puts each run. Fixed protocol instead:
        warm up until the segment-reuse pool is primed, then take k
        samples of a fixed iteration count and report the MEDIAN sample —
        one slow sample (a box-load spike or a pool miss) loses to the
        clean majority, so the number is comparable run to run."""
        import statistics

        for _ in range(3):  # warmup: prime the segment-reuse pool
            put_big()
        reps, iters = 5, 4
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                put_big()
            samples.append(iters * big.nbytes / (time.perf_counter() - t0) / 1e9)
        return statistics.median(samples)

    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    def pg_cycle():
        pg = placement_group([{"CPU": 0.01}], strategy="PACK")
        pg.ready(timeout=30)
        remove_placement_group(pg)
        return 1

    # single-task submit→get round-trip latency distribution (ms): the
    # submit hot path's latency view (throughput metrics above hide tail
    # behavior behind batching)
    def submit_get_latency(n: int = 300):
        samples = []
        for _ in range(n):
            t0 = time.perf_counter()
            ray_tpu.get(noop.remote(), timeout=60)
            samples.append((time.perf_counter() - t0) * 1000.0)
        return samples

    try:
        submit_get_latency(20)  # warmup
        lat = submit_get_latency()
        p50, p99 = _percentiles(lat, (0.50, 0.99))
        results["submit_get_latency_p50_p99"] = {
            "value": round(p50, 3),
            "p99": round(p99, 3),
            "unit": "ms",
        }
    except Exception as e:  # noqa: BLE001
        results["submit_get_latency_p50_p99"] = {"error": repr(e)}
    print(
        f"  submit_get_latency_p50_p99: {results['submit_get_latency_p50_p99']}",
        file=sys.stderr, flush=True,
    )

    runtime_metrics = {
        "tasks_sync_per_s": (tasks_sync, "tasks/s"),
        "tasks_async_per_s": (tasks_async, "tasks/s"),
        "actor_calls_sync_per_s": (actor_sync, "calls/s"),
        "actor_calls_async_per_s": (actor_async, "calls/s"),
        "async_actor_calls_sync_per_s": (async_actor_sync, "calls/s"),
        "put_small_per_s": (put_small, "puts/s"),
        "get_small_per_s": (get_small, "gets/s"),
        "pg_create_remove_per_s": (pg_cycle, "PGs/s"),
    }
    for name, (fn, unit) in runtime_metrics.items():
        try:
            v = _timeit(fn)
            results[name] = {"value": round(v, 2), "unit": unit}
        except Exception as e:  # noqa: BLE001
            results[name] = {"error": repr(e)}
        print(f"  {name}: {results[name]}", file=sys.stderr, flush=True)

    try:
        gbps = put_big_gbps()
        results["put_gbps"] = {
            "value": round(gbps, 3),
            "unit": "GB/s (64 MiB puts, median of 5 samples × 4 fixed iters)",
        }
    except Exception as e:  # noqa: BLE001
        results["put_gbps"] = {"error": repr(e)}
    print(f"  put_gbps: {results['put_gbps']}", file=sys.stderr, flush=True)

    ray_tpu.shutdown()


def bench_data_plane(results: Dict[str, Dict]) -> None:
    """Cross-node data-plane throughput on the RAW (zero-copy) framing.

    Phase 1 — pull: DETERMINISTIC first-pull timings over fixed object
    sizes (median of 3 distinct objects per size), measured straight
    against the destination daemon's ``pull_object`` — the chunked
    pull-manager path, no task machinery in the loop. 256 MiB probes the
    admission-budget-sized regime. Methodology note: the honest ceiling
    for these numbers is the RAW ASYNCIO LOOPBACK FLOOR — what a bare
    asyncio reader/writer pair moves over 127.0.0.1 on this box (~0.29
    GB/s when measured for ISSUE 11) — not the NIC; see
    BENCH_DETAILS.json notes.

    Phase 2 — shuffle_gbps: the 2-phase map/reduce exchange
    (``data/shuffle.py``) over a 2-node cluster; partition bytes ride
    the same RAW chunk path via reducer arg-fetch, so this is the
    many-objects/many-pulls view of the same substrate."""
    import statistics

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.rpc import IoThread, RpcClient

    cluster = Cluster(num_cpus=2)
    io = None
    try:
        cluster.add_node(num_cpus=2)
        time.sleep(1.0)
        ray_tpu.init(address=cluster.address)
        head_daemon = ("127.0.0.1", cluster.head_daemon_port)
        # the added node's daemon = the one that is not the head's
        dest = next(
            (n["host"], n["port"])
            for n in ray_tpu.nodes()
            if n["port"] != cluster.head_daemon_port
        )
        io = IoThread("bench-pull-io")
        client = RpcClient(dest[0], dest[1], name="bench-dest", role="noded")
        for size_mb in (8, 64, 256):
            size = size_mb * 1024 * 1024
            reps = 5 if size_mb <= 64 else 3
            # DISTINCT objects, ALL created before the timed window:
            # every pull is a genuine first transfer (no local-hit
            # shortcut), and the driver's 2×size/rep of put-side memory
            # churn happens outside the measurement — pull reps measure
            # the transfer, not the put's page-teardown wake (part of
            # the put_gbps variance fix, ISSUE 11)
            refs = [
                ray_tpu.put(np.full(size, rep + 1, dtype=np.uint8))
                for rep in range(reps)
            ]
            time.sleep(1.0)
            samples = []
            for ref in refs:
                t0 = time.perf_counter()
                reply = io.run(
                    client.call(
                        "pull_object",
                        {
                            "object_id": ref.id().binary(),
                            "sources": [head_daemon],
                            "deadline_s": 120.0,
                        },
                        timeout=120,
                    ),
                    timeout=130,
                )
                dt = time.perf_counter() - t0
                assert reply and reply.get("segment"), reply
                samples.append(size / dt / 1e9)
            for ref in refs:
                ray_tpu.free(ref)
            results[f"pull_gbps_{size_mb}mb"] = {
                "value": round(statistics.median(samples), 3),
                "unit": f"GB/s (cross-node pull, {size_mb} MiB, "
                        f"median of {reps})",
            }
            print(
                f"  pull_gbps_{size_mb}mb: {results[f'pull_gbps_{size_mb}mb']}",
                file=sys.stderr, flush=True,
            )
        io.run(client.close())

        # -- streaming shuffle (multi-node exchange over the RAW path) --
        from ray_tpu.data.block import block_num_rows, normalize_block
        from ray_tpu.data.shuffle import shuffle_exchange

        n_blocks, rows = 8, 2 * 1024 * 1024  # 8 × 16 MiB float64 blocks
        dataset_bytes = n_blocks * rows * 8
        block_refs = [
            ray_tpu.put(normalize_block(np.random.RandomState(i).rand(rows)))
            for i in range(n_blocks)
        ]
        # warmup exchange on a small slice: worker pool + template caches
        ray_tpu.get(
            shuffle_exchange(block_refs[:2], seed=1), timeout=180
        )
        t0 = time.perf_counter()
        out = ray_tpu.get(
            shuffle_exchange(block_refs, seed=2), timeout=300
        )
        wall = time.perf_counter() - t0
        assert sum(block_num_rows(b) for b in out) == n_blocks * rows
        results["shuffle_gbps"] = {
            "value": round(dataset_bytes / wall / 1e9, 3),
            "unit": f"GB/s ({dataset_bytes >> 20} MiB dataset through the "
                    "2-phase exchange, 2 nodes)",
        }
        print(
            f"  shuffle_gbps: {results['shuffle_gbps']}",
            file=sys.stderr, flush=True,
        )
    finally:
        if io is not None:
            io.stop()
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()


def _collect_slo_block(results: Dict[str, Dict], phase: str, deployments) -> None:
    """SLO-ledger block (ISSUE 15): per-deployment TTFT/ITL/e2e
    p50/p99/p99.9 plus the goodput fraction, read from
    ``serve.slo_report()`` while the phase's cluster is still up — the
    first latency-DISTRIBUTION record in the trajectory files and the
    baseline the ROADMAP item 8 traffic simulator grades against."""
    from ray_tpu import serve

    try:
        rep = serve.slo_report(flight_limit=10)
    except Exception as e:  # noqa: BLE001 — the block is additive
        results.setdefault("slo", {})[phase] = {"error": repr(e)}
        return
    block: Dict[str, Dict] = {}
    for name in deployments:
        d = (rep.get("deployments") or {}).get(name)
        if not d:
            continue
        block[name] = {
            "ttft_s": d.get("ttft_s"),
            "itl_s": d.get("itl_s"),
            "e2e_s": d.get("e2e_s"),
            "goodput_tokens": d.get("goodput_tokens"),
            "fault_tokens": d.get("fault_tokens"),
            "goodput_fraction": d.get("goodput_fraction"),
            "books_balanced": d.get("books_balanced"),
        }
    results.setdefault("slo", {})[phase] = block
    print(f"  slo[{phase}]: {json.dumps(block)}", file=sys.stderr, flush=True)


def bench_serve_llm(results: Dict[str, Dict]) -> None:
    """LLM serving engine on the toy config, measured through the FULL
    serve streaming path (router dispatch + streaming generator + engine
    continuous batching) — the number a serving deployment would see,
    not the bare decode-step rate. CPU-runnable; on the real chip the
    same harness reports chip decode throughput."""
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:
        ec = EngineConfig(
            num_blocks=64, block_size=8, prefill_buckets=(8, 16, 32),
            decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        )
        dep = serve.llm_deployment(LlamaConfig.tiny(), engine=ec)
        handle = serve.run(dep.bind())
        # warmup: bucket compiles happened at replica init; run one
        # stream so the router/streaming path is warm too
        list(handle.stream(
            {"prompt": [1, 2, 3], "max_new_tokens": 4},
            _method="generate", _timeout=300,
        ))

        n, new_tokens = 8, 32
        ttfts: list = []
        counts: list = []
        lock = threading.Lock()

        def consume(i: int) -> None:
            t0 = time.perf_counter()
            first = None
            c = 0
            for _ in handle.stream(
                {"prompt": [1 + i, 2, 3, 4 + i], "max_new_tokens": new_tokens},
                _method="generate", _timeout=300,
            ):
                if first is None:
                    first = time.perf_counter() - t0
                c += 1
            with lock:
                if first is not None:
                    ttfts.append(first)
                counts.append(c)

        start = time.perf_counter()
        threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        total = sum(counts)
        results["serve_llm_tokens_per_s"] = {
            "value": round(total / wall, 2),
            "unit": f"tokens/s (toy config, {n} concurrent streams)",
        }
        if ttfts:
            p50, p99 = _percentiles(ttfts, (0.50, 0.99))
            results["serve_llm_ttft_p50_p99"] = {
                "value": round(p50 * 1000, 1),
                "p99": round(p99 * 1000, 1),
                "unit": "ms",
            }
        for k in ("serve_llm_tokens_per_s", "serve_llm_ttft_p50_p99"):
            if k in results:
                print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)

        # -- prefix caching + multi-replica scale-out (ISSUE 7). Both run
        # on a BEEFIER config than the tiny one above: on a fast CPU box
        # the toy model's prefill/decode hides under routing overhead, so
        # neither the warm-TTFT win nor replica scaling would be
        # attributable to the engine. One deployment serves all phases;
        # the scale-up is an in-place (version-pinned) redeploy so the
        # warm replica and its prefix cache survive.
        import numpy as np

        bcfg = LlamaConfig.tiny(
            dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_hidden=512,
            max_seq_len=512,
        )
        bec = EngineConfig(
            num_blocks=96, block_size=16, prefill_buckets=(16, 64, 512),
            decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        )
        bdep = serve.llm_deployment(
            bcfg, engine=bec, name="llm_scale", route_prefix="/llm_scale",
            version="bench", num_replicas=1,
        )
        bhandle = serve.run(bdep.bind())
        rs5 = np.random.RandomState(5)
        # three DISTINCT 440-token system prompts: each cold sample must
        # be a genuinely first-seen prefix (a shared body would let cold
        # samples 2..n hit the cache sample 1 populated and poison the
        # cold baseline)
        bodies = [
            [int(x) for x in rs5.randint(1, 255, size=440)] for _ in range(3)
        ]

        def ttft_of(prompt) -> float:
            t0 = time.perf_counter()
            for _ in bhandle.stream(
                {"prompt": prompt, "max_new_tokens": 2},
                _method="generate", _timeout=300,
            ):
                return time.perf_counter() - t0
            return float("nan")

        # warm-prefix TTFT: a long shared system prompt; its first use
        # prefills cold, every later conversation on it hits the cache
        ttft_of(bodies[0][:16])  # route/stream path warm, cache cold
        cold_ttfts = [ttft_of(body + [200, 201]) for body in bodies]
        warm_ttfts = [
            ttft_of(bodies[i % 3] + [210 + i, 202]) for i in range(9)
        ]
        est = ray_tpu.get(bhandle.method("engine_stats")(), timeout=60)
        pc = est["prefix_cache"]
        c50, _ = _percentiles(cold_ttfts, (0.50, 0.99))
        w50, w99 = _percentiles(warm_ttfts, (0.50, 0.99))
        results["serve_llm_cold_ttft_p50"] = {
            "value": round(c50 * 1000, 1), "unit": "ms (448-token cold prefill)",
        }
        results["serve_llm_warm_ttft_p50_p99"] = {
            "value": round(w50 * 1000, 1), "p99": round(w99 * 1000, 1),
            "unit": "ms (448-token prompt, prefix-cache warm)",
        }
        results["serve_llm_prefix_hit_rate"] = {
            "value": round(pc["hit_rate"], 4),
            "tokens_saved": pc["tokens_saved_total"],
            "cow_copies": pc["cow_copies_total"],
            "unit": "fraction of admissions served from the prefix cache",
        }
        for k in ("serve_llm_cold_ttft_p50", "serve_llm_warm_ttft_p50_p99",
                  "serve_llm_prefix_hit_rate"):
            print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)

        # replica scaling: the same concurrent-stream workload against 1
        # then 2 replicas of the SAME deployment (distinct prompts so
        # least-outstanding-tokens scoring spreads them)
        def measure_streams(tag: str) -> float:
            cs: list = []

            def consume_b(i: int) -> None:
                c = 0
                for _ in bhandle.stream(
                    {"prompt": [1 + i, 2, 3, 4 + i], "max_new_tokens": new_tokens},
                    _method="generate", _timeout=300,
                ):
                    c += 1
                with lock:
                    cs.append(c)

            t0 = time.perf_counter()
            ths = [threading.Thread(target=consume_b, args=(i,)) for i in range(n)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            wall_b = time.perf_counter() - t0
            return sum(cs) / wall_b

        measure_streams("warmup")
        rep1 = measure_streams("1rep")
        results["serve_llm_scale_1rep_tokens_per_s"] = {
            "value": round(rep1, 2),
            "unit": f"tokens/s ({n} streams, 1 replica, bench config)",
        }
        # in-place scale-up (same pinned version): replica 1 stays warm
        serve.run(serve.llm_deployment(
            bcfg, engine=bec, name="llm_scale", route_prefix="/llm_scale",
            version="bench", num_replicas=2,
        ).bind())
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("llm_scale", min_replicas=2, timeout_s=120),
            timeout=150,
        )
        time.sleep(1.0)  # both replicas' gossip reaches the router
        measure_streams("warmup2")
        rep2 = measure_streams("2rep")
        results["serve_llm_2rep_tokens_per_s"] = {
            "value": round(rep2, 2),
            "unit": f"tokens/s ({n} streams, 2 replicas, bench config)",
            "vs_1rep": round(rep2 / max(rep1, 1e-9), 3),
        }
        for k in ("serve_llm_scale_1rep_tokens_per_s", "serve_llm_2rep_tokens_per_s"):
            print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)

        # -- resumed-stream TTFT (ISSUE 10): kill the replica actively
        # decoding a stream; the router resumes on the survivor with the
        # prompt extended by the delivered tokens. Both replicas are
        # pre-warmed with the shared 440-token body, so the replayed
        # prefix rides the survivor's radix cache — time-to-next-token
        # after the kill should approach the WARM TTFT, demonstrating
        # the prefix-cache-backed recovery win vs a cold re-prefill.
        def _warm_all_replicas() -> None:
            for r in ray_tpu.get(ctrl.get_replicas.remote("llm_scale"), timeout=60):
                gen = r.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(
                    "generate",
                    [{"prompt": bodies[0] + [250], "max_new_tokens": 1}],
                    {}, "",
                )
                for ref in gen:
                    ray_tpu.get(ref, timeout=120)

        def _resume_gap(sample_i: int) -> float:
            ray_tpu.get(
                ctrl.wait_status.remote("llm_scale", min_replicas=2, timeout_s=120),
                timeout=150,
            )
            _warm_all_replicas()
            times: list = []
            killed: dict = {}

            def _killer() -> None:
                while not killed:
                    time.sleep(0.05)
                    if len(times) < 2:
                        continue  # kill only once the stream is mid-flight
                    for r in ray_tpu.get(
                        ctrl.get_replicas.remote("llm_scale"), timeout=30
                    ):
                        try:
                            st = ray_tpu.get(
                                r.handle_request.remote("engine_stats", [], {}, ""),
                                timeout=30,
                            )
                        except Exception:
                            continue
                        if st["scheduler"]["running"] > 0:
                            ray_tpu.kill(r)
                            killed["t"] = time.perf_counter()
                            return

            th = threading.Thread(target=_killer, daemon=True)
            th.start()
            for _ in bhandle.stream(
                {"prompt": bodies[0] + [251, 252 + sample_i],
                 "max_new_tokens": 24},
                _method="generate", _timeout=300,
            ):
                times.append(time.perf_counter())
            killed.setdefault("t", None)
            th.join(timeout=60)
            if killed.get("t") is None or len(times) < 2:
                return float("nan")
            # the resume pause dominates every legitimate inter-token gap
            return max(b - a for a, b in zip(times, times[1:]))

        gaps = [g for g in (_resume_gap(i) for i in range(3)) if g == g]
        if gaps:
            r50, _ = _percentiles(gaps, (0.50, 0.99))
            results["serve_llm_resume_ttft_p50"] = {
                "value": round(r50 * 1000, 1),
                "unit": "ms (replica killed mid-decode; resumed-stream "
                        "time-to-next-token on the prefix-warm survivor)",
                "samples": len(gaps),
                "vs_cold_ttft_p50_ms": results["serve_llm_cold_ttft_p50"]["value"],
            }
            print(
                f"  serve_llm_resume_ttft_p50: {results['serve_llm_resume_ttft_p50']}",
                file=sys.stderr, flush=True,
            )
        # leave the deployment with its target replica count for teardown
        ray_tpu.get(
            ctrl.wait_status.remote("llm_scale", min_replicas=2, timeout_s=120),
            timeout=150,
        )
        _collect_slo_block(results, "serve", ("llm", "llm_scale"))
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()


def bench_serve_llm_spec(results: Dict[str, Dict]) -> None:
    """Speculative decoding (ISSUE 19): the same 8-concurrent-stream
    serve workload shape as ``serve_llm_tokens_per_s``, on a
    speculation-friendly planted prompt, against a PLAIN deployment of
    the identical engine config in the same cluster — so ``vs_plain``
    isolates exactly the propose/batched-verify win (one
    ``paged_verify_step`` advances all 8 slots k+1 positions where plain
    decode advances them 1). The prompt is seeded with the model's own
    greedy continuation: the tiny model decays into repetitive runs, so
    the n-gram proposer's prompt-lookups keep landing (acceptance ~0.6
    at k=4) — the honest analogue of the templated/code traffic
    speculation targets in production. Output bytes are identical either
    way (exact-match acceptance), so tokens/s is the only delta."""
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:
        # the bench config (bcfg rationale in bench_serve_llm): on the
        # 64-token toy model the serve path's per-token streaming cost
        # hides the engine entirely — speculation saves STEPS, so it can
        # only show through when step compute is a real fraction of wall
        cfg = LlamaConfig.tiny(
            dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_hidden=512,
            max_seq_len=512,
        )
        base = dict(
            num_blocks=192, block_size=16, prefill_buckets=(16, 64),
            decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        )
        ph = serve.run(serve.llm_deployment(
            cfg, engine=EngineConfig(**base), name="llm_plain",
            route_prefix="/llm_plain",
        ).bind())
        sh = serve.run(serve.llm_deployment(
            cfg, engine=EngineConfig(**base, speculative_k=4),
            name="llm_spec", route_prefix="/llm_spec",
        ).bind())

        # plant the prompt: 4-token seed + the model's own greedy
        # continuation (fetched through the plain deployment), cut so
        # the measured window sits inside the LONGEST constant run of
        # the continuation — tiny random models settle into limit
        # cycles, and decoding inside one is the proposer's best case
        seed_toks = [1, 2, 3, 4]
        cont = [int(t) for t in ph.stream(
            {"prompt": seed_toks, "max_new_tokens": 280},
            _method="generate", _timeout=600,
        )]
        run_start, run_len, i = 0, 0, 0
        while i < len(cont):
            j = i
            while j < len(cont) and cont[j] == cont[i]:
                j += 1
            if j - i > run_len:
                run_start, run_len = i, j - i
            i = j
        # keep a few run tokens in the prompt so the n-gram lookup has
        # context; stop the window a few short of the run's end
        cut = run_start + min(4, run_len)
        prompt = seed_toks + cont[:cut]
        n = 4
        # decode-dominated window: prefill is identical for both
        # deployments, so the longer the decode run the cleaner vs_plain
        # isolates the speculation win
        new_tokens = max(8, min(96, run_len - 8))

        def measure(handle) -> float:
            """Decode-phase tokens/s: the clock opens once EVERY stream
            has its first token. Prefill is byte-identical across the
            two deployments (speculation only touches decode), so the
            gated ratio must not dilute in shared prefill time."""
            spans: list = []
            lock = threading.Lock()

            def consume(i: int) -> None:
                c, first, last = 0, None, None
                for _ in handle.stream(
                    {"prompt": prompt, "max_new_tokens": new_tokens},
                    _method="generate", _timeout=300,
                ):
                    last = time.perf_counter()
                    if first is None:
                        first = last
                    c += 1
                with lock:
                    spans.append((c, first, last))

            ths = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            t_open = max(s[1] for s in spans)
            t_close = max(s[2] for s in spans)
            return sum(s[0] - 1 for s in spans) / max(t_close - t_open, 1e-9)

        measure(ph)  # route/stream path + prefix cache warm
        measure(sh)
        plain_tps = sorted(measure(ph) for _ in range(3))[1]  # median-of-3
        spec_tps = sorted(measure(sh) for _ in range(3))[1]
        sp = ray_tpu.get(sh.method("engine_stats")(), timeout=60)["speculative"]
        ratio = spec_tps / max(plain_tps, 1e-9)
        results["serve_llm_spec_tokens_per_s"] = {
            "value": round(spec_tps, 2),
            "unit": f"decode tokens/s ({n} streams, planted repetitive prompt)",
            "plain_tokens_per_s": round(plain_tps, 2),
            "vs_plain": round(ratio, 3),
            "meets_gate_1_3x": bool(ratio >= 1.3),
        }
        results["serve_llm_spec_acceptance_rate"] = {
            "value": sp["acceptance_rate"],
            "unit": "accepted/proposed draft tokens (n-gram proposer)",
            "proposed_tokens": sp["proposed_tokens"],
            "accepted_tokens": sp["accepted_tokens"],
            "rollbacks": sp["rollbacks"],
            "k_live": sp["k_live"],
        }
        for k in ("serve_llm_spec_tokens_per_s", "serve_llm_spec_acceptance_rate"):
            print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)
        _collect_slo_block(results, "serve_spec", ("llm_plain", "llm_spec"))
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()


def bench_kv_tier(results: Dict[str, Dict]) -> None:
    """Warm replica restart through the cluster KV prefix tier (ISSUE
    17): SIGKILL the only replica of a tier-enabled deployment, let the
    controller replace it, and measure TTFT for the 440-token shared
    prefix on the replacement. The replacement never prefilled that
    prompt — it adopts the daemon tier registry at start
    (``_tier_recover``) and faults the blocks in over the zero-copy
    path, so restart TTFT should approach the warm number, not the cold
    one."""
    import urllib.request

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:
        cfg = LlamaConfig.tiny(
            dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_hidden=512,
            max_seq_len=512,
        )
        ec = EngineConfig(
            num_blocks=96, block_size=16, prefill_buckets=(16, 64, 512),
            decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        )
        dep = serve.llm_deployment(
            cfg, engine=ec, name="llm_tier", route_prefix="/llm_tier",
            num_replicas=1, kv_tier=True,
        )
        handle = serve.run(dep.bind())
        ctrl = ray_tpu.get_actor("__serve_controller__")
        rs7 = np.random.RandomState(7)
        body = [int(x) for x in rs7.randint(1, 255, size=440)]

        def ttft_of(prompt) -> float:
            t0 = time.perf_counter()
            for _ in handle.stream(
                {"prompt": prompt, "max_new_tokens": 2},
                _method="generate", _timeout=300,
            ):
                return time.perf_counter() - t0
            return float("nan")

        ttft_of(body[:16])  # route/stream path warm, cache + tier cold
        # cold prefill; the prefill write-back publishes the prompt's
        # full blocks into the tier as a side effect
        cold = ttft_of(body + [200, 201])
        time.sleep(2 * GLOBAL_CONFIG.serve_replica_stats_period_s)

        def tier_counters():
            hits = fallbacks = 0.0
            for r in ray_tpu.get(
                ctrl.get_replicas.remote("llm_tier"), timeout=60
            ):
                addr = ray_tpu.get(
                    r.handle_request.remote("metrics_address", [], {}, ""),
                    timeout=60,
                )
                text = urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=10
                ).read().decode()
                for line in text.splitlines():
                    if " " not in line:
                        continue
                    if line.startswith("raytpu_kv_tier_hits_total"):
                        hits += float(line.rsplit(" ", 1)[1])
                    elif line.startswith("raytpu_kv_tier_fallbacks_total"):
                        fallbacks += float(line.rsplit(" ", 1)[1])
            return hits, fallbacks

        samples: list = []
        for i in range(3):
            # SIGKILL the replica; each sample is a fresh restart so the
            # replacement's prefix cache is empty and only the tier can
            # make the shared prefix warm. Measure SERVING TTFT, not the
            # respawn: wait for the replacement actor, then block on a
            # replica call so warmup compiles are behind us.
            old = {
                r.actor_id for r in ray_tpu.get(
                    ctrl.get_replicas.remote("llm_tier"), timeout=60
                )
            }
            for r in ray_tpu.get(
                ctrl.get_replicas.remote("llm_tier"), timeout=60
            ):
                ray_tpu.kill(r)
            deadline = time.monotonic() + 120
            reps = []
            while time.monotonic() < deadline:
                reps = ray_tpu.get(
                    ctrl.get_replicas.remote("llm_tier"), timeout=60
                )
                if reps and all(r.actor_id not in old for r in reps):
                    break
                time.sleep(0.25)
            ray_tpu.get(
                reps[0].handle_request.remote("routing_stats", [], {}, ""),
                timeout=120,
            )
            # recovered adverts need one gossip beat to reach the router
            time.sleep(2 * GLOBAL_CONFIG.serve_replica_stats_period_s)
            g = ttft_of(body + [210 + i, 202])
            if g == g:
                samples.append(g)
        hits, fallbacks = tier_counters()
        if samples:
            w50, _ = _percentiles(samples, (0.50, 0.99))
            results["serve_llm_warm_restart_ttft_p50"] = {
                "value": round(w50 * 1000, 1),
                "unit": "ms (replica SIGKILLed; replacement serves the "
                        "440-token prefix via tier fault-in, no re-prefill)",
                "samples": len(samples),
                "vs_cold_ttft_ms": round(cold * 1000, 1),
            }
        denom = hits + fallbacks
        results["kv_tier_hit_rate"] = {
            "value": round(hits / denom, 4) if denom else None,
            "hits": hits,
            "fallbacks": fallbacks,
            "unit": "tier blocks committed / (committed + fallback rungs), "
                    "final replica generation",
        }
        for k in ("serve_llm_warm_restart_ttft_p50", "kv_tier_hit_rate"):
            if k in results:
                print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)
        _collect_slo_block(results, "kv_tier", ("llm_tier",))
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()


def _bench_chained(attn, q, k, v, iters: int = 30, reps: int = 5) -> float:
    """Seconds per attention call, with iterations CHAINED inside one jit
    (output feeds the next input) and a host readback as the sync point.
    Plain per-call block_until_ready timing is wrong on this hardware:
    dispatch is async behind a high-latency tunnel, so un-chained loops
    measure queue depth, not compute (round-2 numbers exceeded the chip's
    peak FLOP/s). The tunnel also adds a ~130 ms CONSTANT per readback,
    so a single run over-reports per-iter time by overhead/iters (round-4
    MFU was understated this way); timing run(2N) minus run(N) cancels
    the constant (validated: a bf16 8192-matmul then measures ~96% of the
    chip's nominal peak)."""
    import statistics

    import jax
    import jax.numpy as jnp

    def timed(n):
        @jax.jit
        def run(q, k, v):
            def body(i, q):
                return attn(q, k, v).astype(q.dtype)

            return jnp.sum(jax.lax.fori_loop(0, n, body, q).astype(jnp.float32))

        float(run(q, k, v))  # compile + sync
        ts = []
        for _ in range(reps):
            start = time.perf_counter()
            float(run(q, k, v))
            ts.append(time.perf_counter() - start)
        return statistics.median(ts)

    # The diff run is noise-sensitive: when per-iter compute is tiny the
    # two medians can invert. Repeat the (2N, N) pair and take the MEDIAN
    # diff; clamp at a measurable floor instead of returning garbage —
    # the caller reports "below_resolution" rather than erroring.
    diffs = []
    for _ in range(3):
        diffs.append(timed(2 * iters) - timed(iters))
    diff = statistics.median(diffs)
    floor = _MIN_MEASURABLE_S * iters
    if diff < floor:
        return float("nan")
    return diff / iters


#: below this per-diff-run wall time the ~130 ms tunnel constant and
#: scheduler jitter swamp the signal — results are "below_resolution"
_MIN_MEASURABLE_S = 2e-6


def _maybe_invalid(entry: Dict, dt: float) -> Dict:
    import math as _math

    if _math.isnan(dt) or _math.isinf(dt):
        # not an error: the diff-run subtraction bottomed out under the
        # timing floor even after repeated medians — the quantity is
        # real, this box just can't resolve it
        return {"value": None, "below_resolution": True, "unit": entry.get("unit", "")}
    return entry


def bench_tpu(results: Dict[str, Dict]) -> None:
    """Compute benchmarks on the default jax backend (the real chip when
    run without platform overrides)."""
    import functools

    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    results["jax_backend"] = {"value": backend, "unit": ""}
    on_tpu = backend == "tpu"

    # MFU denominator: the chip's public dense-bf16 peak
    from ray_tpu.accelerators.tpu import peak_bf16_tflops

    peak = None
    if on_tpu:
        kind = jax.devices()[0].device_kind
        peak = peak_bf16_tflops(kind)
        results["chip"] = {"value": kind, "unit": ""}
        results["chip_peak_tflops"] = {"value": peak, "unit": "TFLOP/s bf16"}

    def mfu(tflops: float) -> Optional[float]:
        return round(tflops / peak, 4) if peak else None

    # flash attention vs XLA, short + long context. The XLA baseline is
    # jax.nn.dot_product_attention — a tuned path a user would actually
    # reach for — NOT the naive O(S^2)-materializing oracle (which HBM-
    # thrashes at long context and would flatter the kernel).
    from ray_tpu.ops.attention import (
        _pick_block,
        default_blocks,
        default_bwd_blocks,
        flash_attention,
    )

    def xla_dpa(q, k, v):
        # our layout is (b, h, s, d); jax.nn wants (b, s, h, d)
        out = jax.nn.dot_product_attention(
            q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2), is_causal=True
        )
        return out.swapaxes(1, 2)

    impl = "pallas" if on_tpu else "xla"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    cases = [(2048, 4), (8192, 1)] if on_tpu else [(512, 2)]
    for s, b in cases:
        h, d = 16, 128
        q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), dtype)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), dtype)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), dtype)
        flops = 4.0 * b * h * s * s * d * 0.5  # causal ≈ half the score matrix
        fa = functools.partial(flash_attention, causal=True, impl=impl)
        for name, fn in [(f"flash_attention_s{s}", fa), (f"xla_attention_s{s}", xla_dpa)]:
            iters = 60 if s <= 2048 else 20
            dt = _bench_chained(fn, q, k, v, iters=iters)
            tf = round(flops / dt / 1e12, 2)
            results[f"{name}_tflops"] = _maybe_invalid(
                {"value": tf, "unit": "TFLOP/s", "mfu": mfu(tf)}, dt
            )
            print(f"  {name}: {results[f'{name}_tflops']}", file=sys.stderr, flush=True)

        # fwd+bwd: grad of sum(flash) = 2 fwd + 5 bwd matmuls = 3.5x fwd
        # flops. Grad wrt ALL inputs — q-only would let jit DCE the whole
        # dk/dv kernel and inflate the number ~1.4x. The backward runs
        # its per-bucket tuned blocks (``default_bwd_blocks``), no longer
        # the forward-shaped ones — the choice is emitted alongside the
        # MFU so real-chip sweeps can re-anchor the bucket table.
        def fa_grad(q, k, v):
            dq, dk, dv = jax.grad(
                lambda q, k, v: jnp.sum(fa(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2),
            )(q, k, v)
            return dq + dk + dv

        iters = 30 if s <= 2048 else 10
        dt = _bench_chained(fa_grad, q, k, v, iters=iters)
        tf = round(3.5 * flops / dt / 1e12, 2)
        results[f"flash_fwdbwd_s{s}_tflops"] = _maybe_invalid(
            {
                "value": tf,
                "unit": "TFLOP/s",
                "mfu": mfu(tf),
                # _pick_block-RESOLVED choices — the table entry clamps
                # to a divisor of s, and re-anchoring the bucket table
                # must attribute the MFU to the blocks that actually ran
                "fwd_blocks": [_pick_block(s, w) for w in default_blocks(s)],
                "bwd_blocks": [_pick_block(s, w) for w in default_bwd_blocks(s)],
            },
            dt,
        )
        print(f"  flash_fwdbwd_s{s}: {results[f'flash_fwdbwd_s{s}_tflops']}", file=sys.stderr, flush=True)

    # CNN forward (the DQN/Atari image path): conv stack throughput on
    # the MXU (reference rllib CNN defaults; ray_tpu.rl.models)
    from ray_tpu.rl.models import apply_cnn_q, init_cnn

    bb, hh, ww, cc = (256, 84, 84, 4) if on_tpu else (8, 16, 16, 3)
    cnn_params = init_cnn(jax.random.PRNGKey(3), (hh, ww, cc), 6, heads=("q",))
    if on_tpu:
        cnn_params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
            cnn_params,
        )
    obs0 = jax.random.uniform(jax.random.PRNGKey(4), (bb, hh, ww, cc),
                              jnp.bfloat16 if on_tpu else jnp.float32)

    def cnn_step(x, _k, _v):
        q = apply_cnn_q(cnn_params, x)
        # zero-weight data dep chains the iterations without growing x
        return x + (0 * q.sum()).astype(x.dtype)

    iters = 60 if on_tpu else 5
    dt = _bench_chained(cnn_step, obs0, obs0, obs0, iters=iters)
    results["cnn_forward_images_per_s"] = _maybe_invalid(
        {"value": round(bb / dt, 1), "unit": "images/s (84x84x4 batch 256)"}, dt
    )
    print(f"  cnn_forward_images_per_s: {results['cnn_forward_images_per_s']}", file=sys.stderr, flush=True)

    # Llama train step — the UNIFIED named-sharding step (ISSUE 14): the
    # same ``rules``-driven constrained step the multichip dryrun gates,
    # run over every local device (fsdp over all chips; a 1-device box
    # degenerates to the single-chip step with the constraints compiled
    # in). Selective remat on TPU: save dots + flash outputs, recompute
    # only the elementwise tail — the fwd+bwd roofline config.
    import optax

    from ray_tpu.models.llama import (
        LlamaConfig,
        batch_sharding,
        init_sharded,
        make_train_step,
        param_count,
    )
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.parallel.sharding import ddp_rules, fsdp_rules

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=24, n_heads=16, n_kv_heads=16,
            mlp_hidden=4096, max_seq_len=2048, dtype=jnp.bfloat16,
        )
        batch, seq, remat = 8, 2048, "selective"
    else:
        cfg = LlamaConfig(
            vocab_size=8192, dim=512, n_layers=8, n_heads=8, n_kv_heads=8,
            mlp_hidden=1536, max_seq_len=1024, dtype=jnp.float32,
        )
        batch, seq, remat = 2, 256, False
    n_dev = len(jax.devices())
    mesh = make_mesh(MeshSpec(fsdp=n_dev), jax.devices())
    rules = fsdp_rules() if n_dev > 1 else ddp_rules()
    opt = optax.adamw(1e-3)
    params, opt_state = init_sharded(cfg, mesh, rules, jax.random.PRNGKey(0), opt)
    n_params = param_count(cfg)
    results["train_model_params"] = {"value": n_params, "unit": "params"}
    results["train_step_config"] = {
        "value": "unified-sharding",
        "devices": n_dev,
        "rules": "fsdp" if n_dev > 1 else "ddp",
        "remat": str(remat),
        "unit": "",
    }
    step = make_train_step(cfg, opt, remat=remat, donate=True, mesh=mesh, rules=rules)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size, jnp.int32)
    bs = batch_sharding(mesh, rules)
    bd = {
        "tokens": jax.device_put(tokens, bs),
        "targets": jax.device_put(tokens, bs),
    }
    state = (params, opt_state)
    state, loss = step(state, bd)  # compile
    float(loss)  # host readback: block_until_ready is unreliable on the tunnel

    def timed(iters):
        nonlocal state
        start = time.perf_counter()
        for _ in range(iters):
            state, loss = step(state, bd)  # state chains: serialized by data dep
        float(loss)
        return time.perf_counter() - start

    # diff-of-runs cancels the tunnel's ~130 ms constant readback cost
    t1 = timed(5)
    t2 = timed(15)
    if t2 - t1 <= 0:
        for k in ("train_tokens_per_s", "train_tflops", "train_mfu"):
            results[k] = {"value": None, "below_resolution": True}
        return
    dt = (t2 - t1) / 10
    tok_s = batch * seq / dt
    # standard 6ND accounting (fwd+bwd; remat recompute not credited);
    # MFU divides by the peak of EVERY device the mesh spans
    train_tflops = 6.0 * n_params * tok_s / 1e12
    results["train_tokens_per_s"] = {"value": round(tok_s, 1), "unit": "tokens/s"}
    results["train_tflops"] = {"value": round(train_tflops, 2), "unit": "TFLOP/s"}
    results["train_mfu"] = {
        "value": mfu(train_tflops / n_dev),
        "unit": f"fraction of {n_dev}-chip peak",
    }
    for k in ("train_tokens_per_s", "train_tflops", "train_mfu"):
        print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)


def bench_ingress(results: Dict[str, Dict]) -> None:
    """HTTP/SSE front door (serve/ingress.py): client-observed TTFT
    through the FULL stack (urllib → aiohttp ingress → token bucket +
    shed policy → router → streaming replica → engine), and goodput
    under an overload mix — one abusive tenant hammering a tight bucket
    while well-behaved tenants stream. Goodput counts only tokens
    DELIVERED to admitted requests; the shed fraction is reported
    alongside (shed requests cost the engines nothing — that is the
    contract the number demonstrates)."""
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.serve.ingress import (
        IngressConfig, IngressShedError, TenantPolicy, http_stream,
        pick_ingress,
    )

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:
        ec = EngineConfig(
            num_blocks=64, block_size=8, prefill_buckets=(8, 16, 32),
            decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        )
        serve.run(serve.llm_deployment(LlamaConfig.tiny(), engine=ec).bind())
        ing_cfg = IngressConfig(
            target="llm",
            tenants={"abuser": TenantPolicy(
                rate=20.0, burst=60.0, tenant_class="batch")},
        )
        serve.run(
            serve.ingress_deployment("llm", ing_cfg, name="ingress").bind(),
            name="ingress",
        )
        addrs = serve.ingress_addresses("ingress")
        # warmup: route + stream path hot
        list(http_stream(addrs[0], {"prompt": [1, 2, 3], "max_new_tokens": 4}))

        n, new_tokens = 8, 32
        ttfts: list = []
        counts: list = []
        sheds = [0]
        lock = threading.Lock()

        def consume(i: int) -> None:
            tenant = f"tenant-{i}"
            addr = pick_ingress(tenant, addrs)
            t0 = time.perf_counter()
            first, c = None, 0
            try:
                for _tok in http_stream(
                    addr,
                    {"prompt": [1 + i, 2, 3, 4 + i],
                     "max_new_tokens": new_tokens},
                    tenant=tenant, connect_timeout=300.0,
                ):
                    if first is None:
                        first = time.perf_counter() - t0
                    c += 1
            except IngressShedError:
                # a well-behaved stream shed under the abuser's pressure
                # still counts as a (zero-token) sample — silently
                # dropping it would inflate the reported goodput
                with lock:
                    sheds[0] += 1
            with lock:
                if first is not None:
                    ttfts.append(first)
                counts.append(c)

        def abuse() -> None:
            addr = pick_ingress("abuser", addrs)
            for _ in range(20):
                try:
                    list(http_stream(
                        addr, {"prompt": [9, 9, 9], "max_new_tokens": 8},
                        tenant="abuser", connect_timeout=300.0,
                    ))
                except IngressShedError:
                    with lock:
                        sheds[0] += 1

        start = time.perf_counter()
        threads = [
            threading.Thread(target=consume, args=(i,)) for i in range(n)
        ] + [threading.Thread(target=abuse)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        if ttfts:
            p50, p99 = _percentiles(ttfts, (0.50, 0.99))
            results["serve_http_ttft_p50_p99"] = {
                "value": round(p50 * 1000, 1), "p99": round(p99 * 1000, 1),
                "unit": f"ms (HTTP SSE through the ingress tier, {n} streams)",
            }
        results["ingress_goodput"] = {
            "value": round(sum(counts) / wall, 2),
            "shed": sheds[0],
            "unit": (
                f"delivered tokens/s ({n} well-behaved streams + 1 abusive "
                "tenant; shed = abuser 429s, zero engine slots consumed)"
            ),
        }
        for k in ("serve_http_ttft_p50_p99", "ingress_goodput"):
            if k in results:
                print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def bench_slo_autopilot(results: Dict[str, Dict]) -> None:
    """SLO autopilot (serve/loadgen.py + controller closed loop): the
    SAME seeded chaos trace — heavy-tailed bursty tenant mix with a
    seeded mid-run replica kill, everything derived from ONE master
    chaos seed — replayed twice: against a static single-replica
    deployment with a static shed watermark, then against the closed
    loop (TTFT-burn autoscaling + ITL-derived shed). Reports TTFT-p99
    attainment for both, the attainment ratio, the autoscaler lag, and
    the master seed that replays the whole run."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.serve import loadgen
    from ray_tpu.serve.config import AutoscalingConfig
    from ray_tpu.serve.ingress import IngressConfig

    MASTER = 20260806
    TTFT_SLO, ITL_SLO = 2.0, 1.0
    spec = loadgen.LoadSpec(
        seed=MASTER,
        duration_s=15.0,
        base_rate_rps=3.0,
        burst_factor=3.0,
        n_tenants=4,
        prompt_min=3,
        prompt_max=16,
        prefix_len=4,
        output_min=4,
        output_max=12,
        chaos_master_seed=MASTER,
        # one mid-run kill per replica LIFETIME (200th decode consult):
        # the static pool eats the stall with its whole capacity gone;
        # the closed loop's scale-out splits the consult stream so the
        # extra replicas outlive the trace and drain the backlog
        replica_chaos="kill_mid_decode:1.0:200:1",
    )
    trace = loadgen.build_trace(spec)

    def one_run(closed_loop: bool):
        # chaos env must be exported BEFORE init so replica processes
        # inherit the (master-derived) fault plans — both runs see the
        # exact same injection schedule
        for k, v in loadgen.chaos_env(spec).items():
            os.environ[k] = v
        ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
        try:
            ec = EngineConfig(
                num_blocks=64, block_size=8, prefill_buckets=(8, 16, 32),
                decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
            )
            autoscale = (
                AutoscalingConfig(
                    min_replicas=1, max_replicas=3,
                    target_ongoing_requests=4.0,
                    target_ttft_p99_s=TTFT_SLO / 2,
                    upscale_delay_s=0.5, downscale_delay_s=60.0,
                )
                if closed_loop
                else None
            )
            serve.run(serve.llm_deployment(
                LlamaConfig.tiny(), engine=ec,
                autoscaling_config=autoscale,
            ).bind())
            ing_cfg = IngressConfig(
                target="llm",
                default_rate=1e6, default_burst=1e6,
                shed_itl_target_s=ITL_SLO if closed_loop else None,
            )
            serve.run(
                serve.ingress_deployment("llm", ing_cfg, name="ingress").bind(),
                name="ingress",
            )
            addrs = serve.ingress_addresses("ingress")
            from ray_tpu.serve.ingress import http_stream
            list(http_stream(
                addrs[0], {"prompt": [1, 2, 3], "max_new_tokens": 4},
            ))  # route + stream path hot before the clock starts
            run = loadgen.run_trace(
                trace, spec=spec, addresses=addrs,
                timeout_s=120.0, status_fn=serve.status,
            )
            return loadgen.score(
                run, ttft_slo_s=TTFT_SLO, itl_slo_s=ITL_SLO,
                report=serve.slo_report(), status=serve.status(),
            )
        finally:
            serve.shutdown()
            ray_tpu.shutdown()
            for k in loadgen.chaos_env(spec):
                os.environ.pop(k, None)
            from ray_tpu.core.config import GLOBAL_CONFIG
            GLOBAL_CONFIG.testing_chaos_seed = 0
            GLOBAL_CONFIG.testing_replica_chaos = ""

    static = one_run(closed_loop=False)
    closed = one_run(closed_loop=True)
    ratio = (
        round(closed["ttft_attainment"] / static["ttft_attainment"], 3)
        if static["ttft_attainment"]
        else None
    )
    results["slo_autopilot_ttft_attainment"] = {
        "value": closed["ttft_attainment"],
        "static": static["ttft_attainment"],
        "vs_static": ratio,
        "ttft_p99_s": {
            "closed_loop": round(closed["ttft"]["p99"], 3),
            "static": round(static["ttft"]["p99"], 3),
        },
        "errors": {"closed_loop": closed["errors"], "static": static["errors"]},
        "autoscaler_lag_s": closed.get("autoscaler_lag_s"),
        "chaos_master_seed": MASTER,
        "repro": closed["repro"],
        "unit": (
            f"TTFT-p99 attainment fraction at {TTFT_SLO}s SLO, "
            f"{len(trace)} seeded requests + mid-run replica kill "
            "(closed loop vs static baseline, identical chaos schedule)"
        ),
    }
    print(
        f"  slo_autopilot_ttft_attainment: "
        f"{results['slo_autopilot_ttft_attainment']}",
        file=sys.stderr, flush=True,
    )


def bench_disagg(results: Dict[str, Dict]) -> None:
    """Disaggregated prefill/decode serving (ISSUE 13): the
    long-prefill-interference experiment the architecture exists for.

    Mixed load — standing short-prompt decode streams sharing replicas
    with repeated LONG prefills — measured twice on the same replica
    count: a monolithic 2-replica deployment (prefills interleave with
    the decode batch on both replicas) vs disaggregated 1 prefill + 1
    decode (the decode replica runs 1-token tail prefills only;
    long-prompt KV arrives as imported blocks over the data plane).
    Reported: decode ITL p99 in both modes (the interference metric and
    its ratio — recorded either way the comparison lands), disagg TTFT
    for the long streams (handoff included), and kv_migration_gbps
    measured directly over the publish→pull→digest→attach path."""
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:
        # beefier-than-toy config (the bench_serve_llm rationale): on a
        # fast CPU box the tiny model's prefill hides under routing
        # overhead and no interference would be attributable
        cfg = LlamaConfig.tiny(
            dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_hidden=512,
            max_seq_len=512,
        )
        ec = EngineConfig(
            num_blocks=96, block_size=16, prefill_buckets=(16, 64, 512),
            decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
            max_new_tokens_default=8,
        )
        rs = np.random.RandomState(13)
        long_prompts = [
            [int(x) for x in rs.randint(1, 255, size=448)] for _ in range(4)
        ]
        n_decode, decode_tokens = 2, 48

        def mixed_load(handle) -> Dict[str, list]:
            """Run the mix; returns decode-stream inter-token gaps and
            long-stream TTFTs."""
            gaps: list = []
            long_ttfts: list = []
            lock = threading.Lock()
            stop = threading.Event()

            def decoder(i: int) -> None:
                t_prev = None
                mine = []
                for _tok in handle.stream(
                    {"prompt": [1 + i, 2, 3], "max_new_tokens": decode_tokens},
                    _method="generate", _timeout=300,
                ):
                    now = time.perf_counter()
                    if t_prev is not None:
                        mine.append(now - t_prev)
                    t_prev = now
                with lock:
                    gaps.extend(mine)

            def prefiller(i: int) -> None:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    for _tok in handle.stream(
                        {"prompt": long_prompts[i % len(long_prompts)] + [i],
                         "max_new_tokens": 2},
                        _method="generate", _timeout=300,
                    ):
                        with lock:
                            long_ttfts.append(time.perf_counter() - t0)
                        break

            decoders = [
                threading.Thread(target=decoder, args=(i,))
                for i in range(n_decode)
            ]
            prefillers = [
                threading.Thread(target=prefiller, args=(i,), daemon=True)
                for i in range(2)
            ]
            for t in prefillers:
                t.start()
            time.sleep(0.5)  # long prefills in flight before decode starts
            for t in decoders:
                t.start()
            for t in decoders:
                t.join(timeout=300)
            stop.set()
            for t in prefillers:
                t.join(timeout=30)
            return {"gaps": gaps, "long_ttfts": long_ttfts}

        # -- monolithic baseline: 2 replicas, both phases everywhere
        mono = serve.llm_deployment(
            cfg, engine=ec, name="llm_mono", num_replicas=2,
            route_prefix="/llm_mono",
        )
        mh = serve.run(mono.bind())
        list(mh.stream({"prompt": [1, 2, 3], "max_new_tokens": 4},
                       _method="generate", _timeout=300))
        mono_m = mixed_load(mh)
        serve.delete("llm_mono")

        # -- disaggregated: same replica count, 1 prefill + 1 decode
        dis = serve.llm_deployment(
            cfg, engine=ec, name="llm_disagg", disaggregated=True,
            prefill_replicas=1, decode_replicas=1,
            route_prefix="/llm_disagg",
        )
        dh = serve.run(dis.bind())
        list(dh.stream({"prompt": long_prompts[0], "max_new_tokens": 2},
                       _method="generate", _timeout=300))
        dis_m = mixed_load(dh)

        if mono_m["gaps"] and dis_m["gaps"]:
            (mono_p99,) = _percentiles(mono_m["gaps"], (0.99,))
            (dis_p99,) = _percentiles(dis_m["gaps"], (0.99,))
            results["mono_itl_p99_ms"] = {
                "value": round(mono_p99 * 1000, 2),
                "unit": "ms (decode ITL p99, monolithic 2-replica, mixed load)",
            }
            results["disagg_itl_p99_ms"] = {
                "value": round(dis_p99 * 1000, 2),
                "unit": "ms (decode ITL p99, disagg 1+1, same mixed load)",
            }
            results["disagg_vs_mono_itl_p99"] = {
                "value": round(mono_p99 / max(dis_p99, 1e-9), 3),
                "unit": "x (>1 = disaggregation shields decode from "
                        "long-prefill interference)",
            }
        if dis_m["long_ttfts"]:
            p50, p99 = _percentiles(dis_m["long_ttfts"], (0.50, 0.99))
            results["disagg_ttft_p50_p99"] = {
                "value": round(p50 * 1000, 1), "p99": round(p99 * 1000, 1),
                "unit": "ms (long-prompt TTFT through the disagg handoff)",
            }

        # -- kv_migration_gbps: the publish → pull → digest-gate →
        # attach path, measured directly (driver has a daemon here)
        from ray_tpu.inference import kv_transfer

        payload_bytes = 32 * 1024 * 1024
        kv = np.frombuffer(
            bytes(bytearray(range(256)) * (payload_bytes // 256)),
            dtype=np.float32,
        ).reshape(2, 4, -1, 16, 4, 16)
        payload = {
            "tokens": list(range(kv.shape[2] * 16)), "kv": kv,
            "block_size": 16,
        }
        samples = []
        for _ in range(3):
            desc = kv_transfer.publish(payload)
            t0 = time.perf_counter()
            fetched = kv_transfer.fetch(desc, timeout_s=120)
            assert fetched.array.nbytes == payload_bytes
            fetched.close()
            samples.append(
                payload_bytes / (time.perf_counter() - t0) / (1024 ** 3)
            )
            kv_transfer.release_export(desc["transfer_id"])
        results["kv_migration_gbps"] = {
            "value": round(sorted(samples)[1], 3),
            "unit": "GB/s (KV payload publish→pull→digest→attach, 32 MiB,"
                    " median of 3)",
        }
        for k in (
            "mono_itl_p99_ms", "disagg_itl_p99_ms", "disagg_vs_mono_itl_p99",
            "disagg_ttft_p50_p99", "kv_migration_gbps",
        ):
            if k in results:
                print(f"  {k}: {results[k]}", file=sys.stderr, flush=True)
        _collect_slo_block(
            results, "disagg", ("llm_disagg", "llm_disagg-prefill")
        )
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def main() -> None:
    results: Dict[str, Dict] = {}
    # Context: baselines were measured on a 64-vCPU m5.16xlarge; record this
    # machine so vs_baseline ratios can be read honestly.
    results["machine_cpus"] = {"value": os.cpu_count() or 1, "unit": "vCPU"}
    print("== runtime microbenchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("runtime", lambda: bench_runtime(results))
    except Exception as e:  # noqa: BLE001
        results["runtime_error"] = {"error": repr(e)}
        print(f"runtime bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== data plane (cross-node pull) ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("data_plane", lambda: bench_data_plane(results))
    except Exception as e:  # noqa: BLE001
        results["data_plane_error"] = {"error": repr(e)}
        print(f"data plane bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== serve LLM benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("serve_llm", lambda: bench_serve_llm(results))
    except Exception as e:  # noqa: BLE001
        results["serve_llm_error"] = {"error": repr(e)}
        print(f"serve llm bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== speculative decoding benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("serve_llm_spec", lambda: bench_serve_llm_spec(results))
    except Exception as e:  # noqa: BLE001
        results["serve_llm_spec_error"] = {"error": repr(e)}
        print(f"spec decode bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== KV tier warm-restart benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace(
            "serve_llm_warm_restart", lambda: bench_kv_tier(results)
        )
    except Exception as e:  # noqa: BLE001
        results["serve_llm_warm_restart_error"] = {"error": repr(e)}
        print(f"kv tier bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== HTTP ingress benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("ingress", lambda: bench_ingress(results))
    except Exception as e:  # noqa: BLE001
        results["ingress_error"] = {"error": repr(e)}
        print(f"ingress bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== SLO autopilot benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("slo_autopilot", lambda: bench_slo_autopilot(results))
    except Exception as e:  # noqa: BLE001
        results["slo_autopilot_error"] = {"error": repr(e)}
        print(f"slo autopilot bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== disaggregated serving benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("disagg", lambda: bench_disagg(results))
    except Exception as e:  # noqa: BLE001
        results["disagg_error"] = {"error": repr(e)}
        print(f"disagg bench failed: {e!r}", file=sys.stderr, flush=True)
    print("== TPU compute benchmarks ==", file=sys.stderr, flush=True)
    try:
        _phase_trace("tpu", lambda: bench_tpu(results))
    except Exception as e:  # noqa: BLE001
        results["tpu_error"] = {"error": repr(e)}
        print(f"tpu bench failed: {e!r}", file=sys.stderr, flush=True)

    for name, r in results.items():
        if name in BASELINES and r.get("value") is not None:
            r["vs_baseline"] = round(r["value"] / BASELINES[name], 3)

    # compact per-metric ratio map: goes into BOTH the details file and
    # the headline stdout line, so trajectory files (which only capture
    # stdout) carry every runtime ratio — no more hand-diffing runs
    runtime_ratios = {
        name: results[name].get("vs_baseline")
        for name in BASELINES
        if name in results
    }
    lat = results.get("submit_get_latency_p50_p99", {})
    if lat.get("value") is not None:
        runtime_ratios["submit_get_latency_p50_ms"] = lat["value"]
        runtime_ratios["submit_get_latency_p99_ms"] = lat.get("p99")
    tps = results.get("serve_llm_tokens_per_s", {})
    if tps.get("value") is not None:
        runtime_ratios["serve_llm_tokens_per_s"] = tps["value"]
    ttft = results.get("serve_llm_ttft_p50_p99", {})
    if ttft.get("value") is not None:
        runtime_ratios["serve_llm_ttft_p50_ms"] = ttft["value"]
        runtime_ratios["serve_llm_ttft_p99_ms"] = ttft.get("p99")
    sp = results.get("serve_llm_spec_tokens_per_s", {})
    if sp.get("value") is not None:
        runtime_ratios["serve_llm_spec_tokens_per_s"] = sp["value"]
        runtime_ratios["serve_llm_spec_vs_plain"] = sp.get("vs_plain")
    ar = results.get("serve_llm_spec_acceptance_rate", {})
    if ar.get("value") is not None:
        runtime_ratios["serve_llm_spec_acceptance_rate"] = ar["value"]
    ap = results.get("slo_autopilot_ttft_attainment", {})
    if ap.get("value") is not None:
        runtime_ratios["slo_autopilot_ttft_attainment"] = ap["value"]
        runtime_ratios["slo_autopilot_vs_static"] = ap.get("vs_static")
    for key, label in (
        ("pull_gbps_8mb", "pull_gbps_8mb"),
        ("pull_gbps_64mb", "pull_gbps_64mb"),
        ("pull_gbps_256mb", "pull_gbps_256mb"),
        ("shuffle_gbps", "shuffle_gbps"),
        ("serve_llm_cold_ttft_p50", "serve_llm_cold_ttft_p50_ms"),
        ("serve_llm_warm_ttft_p50_p99", "serve_llm_warm_ttft_p50_ms"),
        ("serve_llm_prefix_hit_rate", "serve_llm_prefix_hit_rate"),
        ("serve_llm_scale_1rep_tokens_per_s", "serve_llm_scale_1rep_tokens_per_s"),
        ("serve_llm_2rep_tokens_per_s", "serve_llm_2rep_tokens_per_s"),
        ("serve_llm_resume_ttft_p50", "serve_llm_resume_ttft_p50_ms"),
        ("serve_llm_warm_restart_ttft_p50", "serve_llm_warm_restart_ttft_p50_ms"),
        ("kv_tier_hit_rate", "kv_tier_hit_rate"),
        ("serve_http_ttft_p50_p99", "serve_http_ttft_p50_ms"),
        ("ingress_goodput", "ingress_goodput_tokens_per_s"),
        ("mono_itl_p99_ms", "mono_itl_p99_ms"),
        ("disagg_itl_p99_ms", "disagg_itl_p99_ms"),
        ("disagg_vs_mono_itl_p99", "disagg_vs_mono_itl_p99"),
        ("disagg_ttft_p50_p99", "disagg_ttft_p50_ms"),
        ("kv_migration_gbps", "kv_migration_gbps"),
    ):
        v = results.get(key, {})
        if v.get("value") is not None:
            runtime_ratios[label] = v["value"]
    results["runtime_vs_baseline"] = runtime_ratios

    details_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAILS.json")
    with open(details_path, "w") as f:
        json.dump(results, f, indent=1)

    # Headline: TPU training throughput if available, else task throughput.
    # The reference publishes NO TPU tokens/s baseline (BASELINE.json
    # `published: {}`), so the training headline's vs_baseline is honestly
    # null — MFU (details) is the absolute quality measure; the runtime
    # metrics carry real vs_baseline ratios against the 2.22.0 release logs.
    if results.get("train_tokens_per_s", {}).get("value") is not None:
        headline = {
            "metric": "train_tokens_per_s",
            "value": results["train_tokens_per_s"]["value"],
            "unit": "tokens/s",
            "vs_baseline": None,
            "mfu": results.get("train_mfu", {}).get("value"),
            "runtime_vs_baseline": runtime_ratios,
        }
    else:
        r = results.get("tasks_async_per_s", {"value": 0.0})
        headline = {
            "metric": "tasks_async_per_s",
            "value": r.get("value", 0.0),
            "unit": "tasks/s",
            "vs_baseline": r.get("vs_baseline", 0.0),
            "runtime_vs_baseline": runtime_ratios,
        }
    print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
