"""ray_tpu — a TPU-native distributed AI runtime.

Public API parity with the reference (``ray.*``): tasks, actors, a
distributed object store with ownership-based memory management, placement
groups / gang scheduling for TPU slices, plus the AI libraries
(``ray_tpu.data`` / ``.train`` / ``.tune`` / ``.serve`` / ``.rl``) and the
TPU-first parallelism layer (``ray_tpu.parallel`` / ``.ops`` / ``.models``).

Importing ``ray_tpu`` does NOT import jax — the compute-path modules are
lazy so runtime worker processes stay lightweight.
"""

from __future__ import annotations

import inspect as _inspect
from typing import Any, Optional

from ray_tpu._version import version as __version__
from ray_tpu.core import api as _api
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor, kill, method
from ray_tpu.core.api import init, is_initialized, shutdown
from ray_tpu.core.deadline import Deadline, deadline_scope
from ray_tpu.core.exceptions import (
    ActorDiedError,
    ActorError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.refs import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.serialization import deregister_serializer, register_serializer
from ray_tpu.core.task_spec import (
    DefaultScheduling,
    NodeAffinityScheduling,
    NodeLabelScheduling,
    PlacementGroupScheduling,
    SpreadScheduling,
    TaskOptions,
)

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "method",
    "ObjectRef",
    "ActorHandle",
    "nodes",
    "drain_node",
    "cluster_resources",
    "available_resources",
    "cluster_status",
    "free",
    "timeline",
    "Deadline",
    "deadline_scope",
    "__version__",
]


def remote(*args, **kwargs):
    """``@ray_tpu.remote`` decorator for functions and classes.

    Reference: ``ray.remote`` — bare (``@remote``) or parameterized
    (``@remote(num_cpus=2, resources={"TPU": 4})``).
    """

    def make(obj):
        opts = TaskOptions().merged_with(**kwargs)
        if _inspect.isclass(obj):
            return ActorClass(obj, opts)
        return RemoteFunction(obj, opts)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return make(args[0])
    if args:
        raise TypeError("remote() takes keyword arguments only")
    return make


def get(refs, *, timeout: Optional[float] = None):
    # compiled-graph results carry their own channel-backed get
    from ray_tpu.dag.compiled import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        return refs.get(timeout)
    if isinstance(refs, list) and any(isinstance(r, CompiledDAGRef) for r in refs):
        # Mixed list: the plain refs still fetch as ONE batched get (a
        # per-element loop would serialize fetches and reapply the full
        # timeout N times); compiled refs resolve via their channels
        # against the same shared deadline.
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        plain = [r for r in refs if not isinstance(r, CompiledDAGRef)]
        plain_values = iter(
            _api._global_worker().get(plain, timeout=timeout) if plain else []
        )
        out = []
        for r in refs:
            if isinstance(r, CompiledDAGRef):
                left = None if deadline is None else max(0.0, deadline - _time.monotonic())
                out.append(r.get(left))
            else:
                out.append(next(plain_values))
        return out
    return _api._global_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return _api._global_worker().put(value)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None, fetch_local: bool = True):
    return _api._global_worker().wait(refs, num_returns, timeout, fetch_local)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    _api._global_worker().backend.cancel(ref, force, recursive)


def free(refs) -> None:
    if isinstance(refs, ObjectRef):
        refs = [refs]
    _api._global_worker().backend.free([r.id() for r in refs])


def nodes():
    return _api._global_worker().backend.nodes()


def drain_node(node_id, reason: str = "drain requested") -> bool:
    """Gracefully drain a node (preemption-style): it leaves the
    scheduling pool, finishes running work within the drain grace,
    replicates its primary object copies off-node, and exits cleanly.
    Actor restarts it causes consume no ``max_restarts`` budget.

    ``node_id``: hex string (as in ``nodes()[i]["NodeID"]``) or bytes.
    """
    if isinstance(node_id, str):
        node_id = bytes.fromhex(node_id)
    elif isinstance(node_id, NodeID):
        node_id = node_id.binary()
    return _api._global_worker().backend.drain_node(node_id, reason)


def cluster_resources():
    return _api._global_worker().backend.cluster_resources()


def cluster_status(serve_slo: bool = True):
    """Live cluster state in one call (the ``ray list`` equivalent):
    ``{"nodes", "actors", "tasks": {"summary", "recent"}, "objects",
    "placement_groups", "jobs"}`` from the controller's bounded tables.
    Serve replicas are actors — their liveness shows up in ``actors``
    within one resource-sync/poll period. When a serve controller is up
    a ``serve_slo`` section rides along (``serve.slo_report()`` summary;
    a per-replica fan-out — monitoring loops that only want the tables
    should pass ``serve_slo=False``)."""
    backend = _api._global_worker().backend
    fn = getattr(backend, "cluster_status", None)
    if fn is None:
        # local mode: synthesize the same shape from what exists
        out = {
            "nodes": backend.nodes(),
            "actors": [],
            "tasks": {"summary": {}, "recent": []},
            "objects": {},
            "placement_groups": {},
            "jobs": [],
        }
    else:
        out = fn()
    if serve_slo:
        from ray_tpu.util.state import attach_serve_slo

        attach_serve_slo(out)
    return out


def available_resources():
    return _api._global_worker().backend.available_resources()


def list_named_actors(all_namespaces: bool = False):
    return _api._global_worker().backend.list_named_actors(all_namespaces)


def get_runtime_context():
    from ray_tpu.core.runtime_context import RuntimeContext

    return RuntimeContext(_api._global_worker())


def timeline(filename: Optional[str] = None):
    """Chrome-tracing dump of task events (cf. ``ray.timeline``)."""
    from ray_tpu.observability.timeline import dump_timeline

    return dump_timeline(filename)


def __getattr__(name: str):
    # Lazy subpackages (keep `import ray_tpu` jax-free). Only packages that
    # actually exist are advertised; new libraries are added as they land.
    import importlib.util

    if name in ("data", "train", "tune", "serve", "rl", "parallel", "ops", "models", "util", "dag", "observability"):
        if importlib.util.find_spec(f"ray_tpu.{name}") is None:
            raise AttributeError(
                f"ray_tpu.{name} is not available in this build"
            )
        import importlib

        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
