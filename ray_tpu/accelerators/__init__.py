"""Accelerator autodetection registry.

Reference: ``python/ray/_private/accelerators/__init__.py:13-59`` — a
registry of per-family managers consulted by the node daemon at startup
(resource autodetection) and by the worker-launch path (device isolation).
TPU is first-class here; the registry shape still allows other families.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

from ray_tpu.accelerators.base import AcceleratorManager
from ray_tpu.accelerators.tpu import (
    TPUAcceleratorManager,
    pod_type_chips_per_host,
    pod_type_num_chips,
    pod_type_num_hosts,
    set_metadata_fetcher,
    slice_head_resource_name,
)

_MANAGERS: Dict[str, Type[AcceleratorManager]] = {
    "TPU": TPUAcceleratorManager,
}


def get_all_accelerator_managers() -> List[Type[AcceleratorManager]]:
    return list(_MANAGERS.values())


def get_accelerator_manager(resource_name: str) -> Optional[Type[AcceleratorManager]]:
    return _MANAGERS.get(resource_name)


def detect_node_accelerators() -> tuple:
    """(resources, labels) this host contributes, across all families.

    Called by the node daemon on startup; explicit user resources win.
    """
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}
    for mgr in _MANAGERS.values():
        try:
            n = mgr.get_current_node_num_accelerators()
        except Exception:
            n = 0
        if n <= 0:
            continue
        resources[mgr.get_resource_name()] = float(n)
        resources.update(mgr.get_additional_node_resources())
        labels.update(mgr.get_additional_node_labels())
    return resources, labels


__all__ = [
    "AcceleratorManager",
    "TPUAcceleratorManager",
    "detect_node_accelerators",
    "get_accelerator_manager",
    "get_all_accelerator_managers",
    "pod_type_chips_per_host",
    "pod_type_num_chips",
    "pod_type_num_hosts",
    "set_metadata_fetcher",
    "slice_head_resource_name",
]
