"""Accelerator manager interface.

Reference: ``python/ray/_private/accelerators/accelerator.py`` — a static
interface per accelerator family used by the node daemon to autodetect
resources and by the worker launch path to isolate devices per process.
The TPU-native framework keeps the same shape but TPU is the first-class
citizen (reference treats it as one of eight families).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional


class AcceleratorManager(ABC):
    """Per-family detection + isolation hooks (all static/class methods)."""

    @staticmethod
    @abstractmethod
    def get_resource_name() -> str:
        """Resource name this family contributes (e.g. ``"TPU"``)."""

    @staticmethod
    @abstractmethod
    def get_visible_accelerator_ids_env_var() -> str:
        """Env var used to restrict a process to specific devices."""

    @staticmethod
    @abstractmethod
    def get_current_node_num_accelerators() -> int:
        """Autodetect how many accelerators this host has (0 if none)."""

    @staticmethod
    @abstractmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """Family-specific type string (e.g. ``"TPU-V4"``), or None."""

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple:
        """(ok, error_message) for a task/actor requesting ``quantity``."""
        return True, None

    @staticmethod
    @abstractmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        """Restrict THIS process (and its children) to ``ids``."""

    @staticmethod
    @abstractmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        """Currently-visible device ids, or None when unrestricted."""

    @staticmethod
    def get_additional_node_resources() -> dict:
        """Extra resources this family contributes on registration
        (e.g. TPU slice-head gang resources)."""
        return {}

    @staticmethod
    def get_additional_node_labels() -> dict:
        """Node labels contributed on registration."""
        return {}
