"""TPU accelerator manager: autodetection, isolation, slice gang resources.

Reference: ``python/ray/_private/accelerators/tpu.py`` — chip detection via
``/dev/accel*`` / ``/dev/vfio`` (``:31`` area), GCE/GKE metadata probing
(``:19-45``), ``TPU_VISIBLE_CHIPS`` per-process isolation, the
``TPU-{pod_type}-head`` slice-head resource granted on worker 0 of a pod,
and the {1,2,4} valid chips-per-process rule. Re-designed, not ported: the
metadata fetcher is injectable so every path is testable offline, and the
pod math understands v2–v6e naming (cores-suffixed for v2–v5p,
chips-suffixed for v5e/v6e).
"""

from __future__ import annotations

import glob
import logging
import os
from typing import Callable, Dict, List, Optional

from ray_tpu.accelerators.base import AcceleratorManager

logger = logging.getLogger(__name__)

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# libtpu reads these to carve a host's chips into multiple processes.
TPU_CHIPS_PER_PROCESS_BOUNDS_ENV = "TPU_CHIPS_PER_PROCESS_BOUNDS"
TPU_PROCESS_BOUNDS_ENV = "TPU_PROCESS_BOUNDS"

# Explicit overrides (tests / operators without metadata servers).
NUM_CHIPS_OVERRIDE_ENV = "RAY_TPU_NUM_CHIPS"
ACCELERATOR_TYPE_OVERRIDE_ENV = "TPU_ACCELERATOR_TYPE"
WORKER_ID_OVERRIDE_ENV = "TPU_WORKER_ID"
WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"
TPU_NAME_ENV = "TPU_NAME"

# A process may attach to 1, 2, or 4 chips of a host (libtpu constraint;
# reference TPU_VALID_CHIP_OPTIONS).
VALID_CHIPS_PER_PROCESS = (1, 2, 4)

_GCE_METADATA_URL = "http://metadata.google.internal/computeMetadata/v1/instance/"

# chips per host by TPU generation
_CHIPS_PER_HOST = {
    "v2": 4,
    "v3": 4,
    "v4": 4,
    "v5p": 4,
    "v5litepod": 8,
    "v5e": 8,
    "v6e": 8,
}
# generations whose pod-type suffix counts TensorCores (2/chip), not chips
_CORES_SUFFIXED = {"v2", "v3", "v4", "v5p"}

# Public per-chip bf16 peak (dense) in TFLOP/s, keyed by substrings of
# ``jax.Device.device_kind`` — the denominator for MFU reporting. Longest
# match wins ("v5 lite" before "v5").
_PEAK_BF16_TFLOPS = {
    "v2": 46.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}


def peak_bf16_tflops(device_kind: str) -> Optional[float]:
    """Per-chip dense-bf16 peak for a jax ``device_kind`` string (e.g.
    ``"TPU v5 lite"``); None when unknown."""
    kind = device_kind.lower()
    best = None
    best_len = 0
    for key, peak in _PEAK_BF16_TFLOPS.items():
        if key in kind and len(key) > best_len:
            best, best_len = peak, len(key)
    return best


# ---------------------------------------------------------------------------
# Metadata access — injectable for tests (reference probes GCE/GKE metadata)

_metadata_fetcher: Optional[Callable[[str], Optional[str]]] = None


def set_metadata_fetcher(fetcher: Optional[Callable[[str], Optional[str]]]) -> None:
    """Inject a metadata source (tests / non-GCE deployments)."""
    global _metadata_fetcher
    _metadata_fetcher = fetcher


def _fetch_metadata(path: str) -> Optional[str]:
    if _metadata_fetcher is not None:
        return _metadata_fetcher(path)
    try:
        from urllib.request import Request, urlopen

        req = Request(
            _GCE_METADATA_URL + path, headers={"Metadata-Flavor": "Google"}
        )
        with urlopen(req, timeout=1) as resp:  # noqa: S310
            return resp.read().decode()
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Preemption / maintenance-event probe (pluggable via set_metadata_fetcher)

#: metadata path GCE flips from NONE before host maintenance / preemption
MAINTENANCE_EVENT_PATH = "maintenance-event"


def get_current_node_maintenance_event() -> Optional[str]:
    """The pending maintenance event for this host (e.g. ``"TERMINATE_ON_
    HOST_MAINTENANCE"``), ``"NONE"``/None when nothing is scheduled. Uses
    the same injectable metadata fetcher as the rest of TPU detection, so
    tests and non-GCE deployments plug in their own preemption signal."""
    event = _fetch_metadata(MAINTENANCE_EVENT_PATH)
    return event.strip() if event else None


def maintenance_event_imminent() -> bool:
    """True when the platform has announced this host will be reclaimed —
    the node daemon's preemption-probe loop turns this into a drain."""
    event = get_current_node_maintenance_event()
    return bool(event) and event.upper() != "NONE"


# ---------------------------------------------------------------------------
# Pod-type math


def pod_type_num_chips(pod_type: str) -> int:
    """Total chips in a pod slice, from its type string (e.g. v4-32 → 16)."""
    gen, _, suffix = pod_type.partition("-")
    n = int(suffix)
    return n // 2 if gen in _CORES_SUFFIXED else n


def pod_type_chips_per_host(pod_type: str) -> int:
    gen = pod_type.partition("-")[0]
    return _CHIPS_PER_HOST.get(gen, 4)


def pod_type_num_hosts(pod_type: str) -> int:
    chips = pod_type_num_chips(pod_type)
    per_host = pod_type_chips_per_host(pod_type)
    return max(1, chips // per_host)


def slice_head_resource_name(pod_type: str) -> str:
    """Gang resource present only on host 0 of a slice: lets one actor/PG
    claim the whole slice by requesting ``{"TPU-v4-32-head": 1}``."""
    from ray_tpu.core.resources import tpu_slice_head_resource

    return tpu_slice_head_resource(pod_type)


# ---------------------------------------------------------------------------


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        """Chips on this host: env override → device files → metadata."""
        override = os.environ.get(NUM_CHIPS_OVERRIDE_ENV)
        if override:
            return int(override)
        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        try:
            vfio = os.listdir("/dev/vfio")
            chips = [f for f in vfio if f != "vfio"]
            if chips:
                return len(chips)
        except OSError:
            pass
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if pod_type:
            return min(
                pod_type_num_chips(pod_type), pod_type_chips_per_host(pod_type)
            )
        return 0

    @staticmethod
    def get_current_node_tpu_pod_type() -> Optional[str]:
        """Pod/slice type (e.g. ``"v4-32"``): env → GCE/GKE metadata."""
        t = os.environ.get(ACCELERATOR_TYPE_OVERRIDE_ENV)
        if t:
            return t
        t = _fetch_metadata("attributes/accelerator-type")
        if t:
            return t.strip()
        return None

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """Family type string, e.g. ``"TPU-V4"`` (used as a node label)."""
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if not pod_type:
            return None
        gen = pod_type.partition("-")[0]
        return f"TPU-{gen.upper()}"

    @staticmethod
    def get_current_node_tpu_worker_id() -> Optional[int]:
        """This host's index within its slice: env → metadata."""
        wid = os.environ.get(WORKER_ID_OVERRIDE_ENV)
        if wid is not None and wid != "":
            return int(wid)
        wid = _fetch_metadata("attributes/agent-worker-number")
        if wid:
            return int(wid.strip())
        return None

    @staticmethod
    def get_current_node_tpu_name() -> Optional[str]:
        name = os.environ.get(TPU_NAME_ENV)
        if name:
            return name
        name = _fetch_metadata("attributes/instance-id")
        return name.strip() if name else None

    @staticmethod
    def get_num_workers_in_current_tpu_pod() -> Optional[int]:
        """Host count of this slice: hostnames env → pod-type arithmetic."""
        hostnames = os.environ.get(WORKER_HOSTNAMES_ENV)
        if hostnames:
            return len(hostnames.split(","))
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if pod_type:
            return pod_type_num_hosts(pod_type)
        return None

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        if quantity != int(quantity):
            return False, f"TPU request must be a whole number, got {quantity}"
        q = int(quantity)
        # A multi-host request is expressed via slice resources/PGs, not a
        # single worker asking for more chips than one process may hold.
        if q not in VALID_CHIPS_PER_PROCESS and q % 4 != 0:
            return (
                False,
                f"a process can use {VALID_CHIPS_PER_PROCESS} chips (or all "
                f"chips of whole hosts, multiples of 4); got {q}",
            )
        return True, None

    @staticmethod
    def isolation_env(ids: List[str]) -> Dict[str, str]:
        """The complete env-var set for restricting a process to ``ids`` —
        one source of truth for both the spawn path (daemon) and the
        in-process path (set_current_process_visible_accelerator_ids).
        Includes the topology hints for libtpu: without these a process
        holding 1 or 2 chips of a host fails to initialize."""
        env = {TPU_VISIBLE_CHIPS_ENV: ",".join(str(i) for i in ids)}
        n = len(ids)
        if n == 1:
            env[TPU_CHIPS_PER_PROCESS_BOUNDS_ENV] = "1,1,1"
            env[TPU_PROCESS_BOUNDS_ENV] = "1,1,1"
        elif n == 2:
            env[TPU_CHIPS_PER_PROCESS_BOUNDS_ENV] = "1,2,1"
            env[TPU_PROCESS_BOUNDS_ENV] = "1,1,1"
        return env

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        env = TPUAcceleratorManager.isolation_env(ids)
        os.environ.update(env)
        for var in (TPU_CHIPS_PER_PROCESS_BOUNDS_ENV, TPU_PROCESS_BOUNDS_ENV):
            if var not in env:
                os.environ.pop(var, None)

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        raw = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if raw is None:
            return None
        return [s for s in raw.split(",") if s != ""]

    # -- node registration extras ---------------------------------------
    @staticmethod
    def get_additional_node_resources() -> Dict[str, float]:
        """Slice-head gang resource on host 0 of a multi-host slice, plus a
        per-pod-type count resource (reference ``tpu.py`` pod head)."""
        out: Dict[str, float] = {}
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if not pod_type:
            return out
        worker_id = TPUAcceleratorManager.get_current_node_tpu_worker_id()
        # Unknown worker id only implies "head" for single-host slices;
        # on a multi-host slice every host would otherwise advertise the
        # head marker and break the one-gang-per-slice invariant.
        if worker_id == 0 or (worker_id is None and pod_type_num_hosts(pod_type) == 1):
            out[slice_head_resource_name(pod_type)] = 1.0
        return out

    @staticmethod
    def get_additional_node_labels() -> Dict[str, str]:
        out: Dict[str, str] = {}
        accel_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if accel_type:
            out["ray.io/accelerator-type"] = accel_type
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if pod_type:
            out["ray.io/tpu-pod-type"] = pod_type
        name = TPUAcceleratorManager.get_current_node_tpu_name()
        if name:
            out["ray.io/tpu-pod-name"] = name
        wid = TPUAcceleratorManager.get_current_node_tpu_worker_id()
        if wid is not None:
            out["ray.io/tpu-worker-id"] = str(wid)
        return out
