"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference: ``python/ray/autoscaler/`` — ``StandardAutoscaler``
(``_private/autoscaler.py``) + ``resource_demand_scheduler.py``
(bin-packing pending demand onto node types) + the ``NodeProvider`` ABC
with the testable ``FakeMultiNodeProvider``
(``_private/fake_multi_node/node_provider.py:236``).

TPU-first: a node type may be a SLICE — ``hosts > 1`` launches that many
hosts atomically (a TPU pod slice is one schedulable unit; scaling half
a slice is meaningless).
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.config import AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.provider import FakeMultiNodeProvider, NodeProvider

__all__ = [
    "AutoscalerConfig",
    "FakeMultiNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "StandardAutoscaler",
]
