"""StandardAutoscaler: the demand → node-type reconciler.

Reference: ``python/ray/autoscaler/_private/autoscaler.py`` (the update
loop) + ``resource_demand_scheduler.py`` (first-fit bin-packing of
pending resource shapes onto node types). Each pass:

1. snapshot demand from the controller (parked lease shapes, PENDING
   actors, PENDING placement-group bundles) + node utilization,
2. subtract what the LIVE cluster's spare capacity can absorb,
3. first-fit-decreasing pack the remainder onto node types (a TPU slice
   type contributes hosts x resources per launch) and launch,
4. terminate provider nodes idle past ``idle_timeout_s``.

TPU-aware: slices launch/terminate atomically — utilization is judged
per provider NODE (all hosts of a slice idle before any terminate).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.config import AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.provider import NodeProvider

logger = logging.getLogger(__name__)


def _fits(shape: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _subtract(capacity: Dict[str, float], shape: Dict[str, float]) -> None:
    for k, v in shape.items():
        if v > 0:
            capacity[k] = capacity.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, config: AutoscalerConfig, *, backend=None):
        self._provider = provider
        self._config = config
        self._backend = backend  # CoreWorker-ish (controller RPC access)
        self._idle_since: Dict[str, float] = {}  # provider node id -> ts
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_stats: Dict[str, Any] = {}

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="autoscaler"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()  # unblock the interval wait
        if self._thread is not None:
            self._thread.join(timeout=5)

    def kick(self) -> None:
        """Run a reconcile pass NOW instead of at the next interval tick.

        Demand-side controllers (e.g. the serve controller raising a
        replica target on TTFT budget burn) call this so the node
        reconciler's share of autoscaler lag is one pass, not up to a
        full ``update_interval_s``."""
        self._kick.set()

    def stats(self) -> Dict[str, Any]:
        """Summary of the most recent reconcile pass (empty before the
        first): wall timestamp, pass duration, demand/unmet shape
        counts, launches by node type, and idle terminations."""
        return dict(self._last_stats)

    def _loop(self) -> None:
        while True:
            self._kick.wait(self._config.update_interval_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self.update()
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("autoscaler update failed")

    # -- one reconcile pass ---------------------------------------------
    def _demand(self) -> Dict[str, Any]:
        backend = self._backend
        if backend is None:
            from ray_tpu.core.api import _global_worker

            backend = _global_worker().backend
        return backend.io.run(
            backend.controller.call("autoscaler_demand", timeout=10), timeout=15
        )

    def update(self) -> None:
        pass_t0 = time.monotonic()
        snap = self._demand()
        shapes: List[Dict[str, float]] = (
            list(snap["pending_tasks"])
            + list(snap["pending_actors"])
            + list(snap["pending_bundles"])
        )
        # Preemption-aware replacement: a DRAINING node's workload must
        # land somewhere else BEFORE the kill — count each draining
        # node's full capacity as demand so the replacement launches the
        # moment the warning arrives, not after the node dies and its
        # work re-queues (arXiv:2605.25645: replacement lead time
        # dominates effective goodput on spot slices).
        draining = [
            n for n in snap["nodes"] if n["alive"] and n.get("state") == "DRAINING"
        ]
        shapes.extend(dict(n["total"]) for n in draining)
        provider_nodes = self._provider.non_terminated_nodes()
        # SLICES are the unit: group host records by launch group
        groups: Dict[str, List[Dict[str, Any]]] = {}
        for r in provider_nodes:
            groups.setdefault(r.get("group", r["id"]), []).append(r)

        # 2. live spare capacity absorbs demand first (per-node fitting).
        # Draining nodes contribute NO spare capacity: nothing new may be
        # packed onto a node that is about to disappear.
        spare: List[Dict[str, float]] = [
            dict(n["available"])
            for n in snap["nodes"]
            if n["alive"] and n.get("state") != "DRAINING"
        ]
        unmet: List[Dict[str, float]] = []
        for shape in sorted(shapes, key=lambda s: -sum(s.values())):
            placed = False
            for cap in spare:
                if _fits(shape, cap):
                    _subtract(cap, shape)
                    placed = True
                    break
            if not placed:
                unmet.append(shape)

        # 3. pack unmet demand onto node types; launch. Counting is per
        # SLICE (launch group), not per host — max_workers bounds slices.
        # Groups whose every host is draining don't count against the
        # caps: their replacement must be launchable NOW, not after the
        # preempted slice finally dies and frees its slot.
        draining_ids = {n["node_id"] for n in draining}
        launches: List[NodeTypeConfig] = []
        counts: Dict[str, int] = {}
        active_groups = 0
        for grp in groups.values():
            if all(r.get("node_id_hex") in draining_ids for r in grp):
                continue
            active_groups += 1
            counts[grp[0]["node_type"]] = counts.get(grp[0]["node_type"], 0) + 1
        # Booting supply credit (reference resource_demand_scheduler's
        # "upcoming nodes"): provider nodes not yet in the controller
        # snapshot are capacity in flight — without seeding them here,
        # every reconcile pass during a node's boot re-launches for the
        # SAME unmet demand until the max_workers caps bite. Only nodes
        # that NEVER joined count (a snapshot row — alive or dead —
        # means joined; dead ones are losses, not boot-pending), and
        # the credit expires after boot_grace_s so a hung launch stops
        # suppressing replacements.
        known_ids = {n["node_id"] for n in snap["nodes"]}
        types_by_name = {t.name: t for t in self._config.node_types}
        now_wall = time.time()
        virtual: List[Dict[str, float]] = []
        for rec in provider_nodes:
            nid = rec.get("node_id_hex")
            if nid is not None and nid in known_ids:
                continue
            launched = rec.get("launched_at")
            if launched is not None and now_wall - launched > self._config.boot_grace_s:
                continue  # boot presumed failed
            nt = types_by_name.get(rec.get("node_type"))
            if nt is not None:
                virtual.append(dict(nt.resources))
        for shape in unmet:
            placed = False
            for cap in virtual:
                if _fits(shape, cap):
                    _subtract(cap, shape)
                    placed = True
                    break
            if placed:
                continue
            nt = self._pick_type(shape, counts, active_groups + len(launches))
            if nt is None:
                logger.warning("demand %s unschedulable on any node type", shape)
                continue
            counts[nt.name] = counts.get(nt.name, 0) + 1
            launches.append(nt)
            for _h in range(max(1, nt.hosts)):
                cap = dict(nt.resources)
                virtual.append(cap)
            # place this shape on the fresh capacity
            for cap in virtual:
                if _fits(shape, cap):
                    _subtract(cap, shape)
                    break
        for nt in launches:
            logger.info("scaling up: launching %s (%d host(s))", nt.name, nt.hosts)
            self._provider.create_node(nt)

        # 4. terminate idle slices (never below min_workers). A slice is
        # idle only when EVERY host is idle — half-terminating a TPU
        # slice would leave a meaningless remnant.
        now = time.monotonic()
        node_rows = {n["node_id"]: n for n in snap["nodes"]}
        min_by_type = {t.name: t.min_workers for t in self._config.node_types}
        terminated = 0
        for gid, members in groups.items():
            busy = bool(shapes)
            for rec in members:
                row = node_rows.get(rec.get("node_id_hex"))
                if row is None or not row["alive"]:
                    busy = True  # still joining (or lost): don't judge idle
                    break
                if any(
                    row["available"].get(k, 0.0) < v
                    for k, v in row["total"].items()
                ):
                    busy = True
                    break
            if busy:
                self._idle_since.pop(gid, None)
                continue
            first_idle = self._idle_since.setdefault(gid, now)
            if now - first_idle < self._config.idle_timeout_s:
                continue
            ntype = members[0]["node_type"]
            if counts.get(ntype, 0) <= min_by_type.get(ntype, 0):
                continue
            logger.info("scaling down: terminating idle slice %s", gid)
            counts[ntype] = counts.get(ntype, 1) - 1
            self._idle_since.pop(gid, None)
            terminated += 1
            for rec in members:
                self._provider.terminate_node(rec["id"])

        launched_by_type: Dict[str, int] = {}
        for nt in launches:
            launched_by_type[nt.name] = launched_by_type.get(nt.name, 0) + 1
        self._last_stats = {
            "ts": time.time(),
            "pass_duration_s": round(time.monotonic() - pass_t0, 6),
            "demand_shapes": len(shapes),
            "unmet_shapes": len(unmet),
            "launches": launched_by_type,
            "terminated_slices": terminated,
        }

    def _pick_type(
        self, shape: Dict[str, float], counts: Dict[str, int], total_slices: int
    ) -> Optional[NodeTypeConfig]:
        if total_slices >= self._config.max_workers:
            return None
        best: Optional[NodeTypeConfig] = None
        for nt in self._config.node_types:
            if counts.get(nt.name, 0) >= nt.max_workers:
                continue
            if not _fits(shape, nt.resources):
                continue
            # smallest type that fits (first-fit-decreasing flavor)
            if best is None or sum(nt.resources.values()) < sum(best.resources.values()):
                best = nt
        return best
