"""Autoscaler configuration.

Reference: the ``available_node_types`` section of the cluster YAML
(``python/ray/autoscaler/ray-schema.json``) reduced to what scaling
decisions actually consume: per-type resources, instance bounds, and the
slice size for TPU pod types."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class NodeTypeConfig:
    name: str
    #: resources ONE host of this type advertises (e.g. {"CPU": 8} or
    #: {"CPU": 8, "TPU": 4})
    resources: Dict[str, float]
    max_workers: int = 4
    min_workers: int = 0
    #: hosts launched atomically per node of this type (TPU slice size in
    #: hosts; 1 for plain CPU/GPU boxes)
    hosts: int = 1


@dataclass
class AutoscalerConfig:
    node_types: List[NodeTypeConfig] = field(default_factory=list)
    #: terminate a provider node after this long at zero utilization
    idle_timeout_s: float = 30.0
    #: reconcile interval
    update_interval_s: float = 1.0
    #: cluster-wide cap on provider-launched nodes
    max_workers: int = 8
    #: how long a launched node gets credited as booting supply before
    #: it's treated as failed (stops double-launching during boot
    #: without trusting a hung/dead launch forever)
    boot_grace_s: float = 120.0
