"""Node providers: how the autoscaler actually gets machines.

Reference: ``python/ray/autoscaler/node_provider.py`` (the ABC cloud
integrations implement) and ``_private/fake_multi_node/node_provider.py:236``
— a provider that launches "nodes" as LOCAL PROCESSES so the scaling
logic is testable with no cloud. Here the fake provider spawns real node
daemons (``cluster_backend.spawn_node``) against the live controller, so
scaled-up capacity genuinely schedules work."""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.config import NodeTypeConfig


class NodeProvider(ABC):
    """Launch/terminate nodes of configured types."""

    @abstractmethod
    def create_node(self, node_type: NodeTypeConfig) -> List[str]:
        """Launch ONE node of ``node_type`` (all its hosts, atomically
        for slices); returns provider node ids (one per host)."""

    @abstractmethod
    def terminate_node(self, provider_id: str) -> None: ...

    @abstractmethod
    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        """[{id, node_type, launched_at, node_id_hex?}] for live nodes."""


class FakeMultiNodeProvider(NodeProvider):
    """Nodes are local node-daemon processes joined to the controller —
    the load-bearing test double (everything above it is the real
    autoscaler against real scheduling)."""

    def __init__(self, controller_addr: str):
        self._controller_addr = controller_addr
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._seq = 0
        self._lock = threading.Lock()

    def create_node(self, node_type: NodeTypeConfig) -> List[str]:
        from ray_tpu.core.cluster_backend import spawn_node

        with self._lock:
            self._seq += 1
            group = f"{node_type.name}-{self._seq}"
        ids = []
        for h in range(max(1, node_type.hosts)):
            proc = spawn_node(
                self._controller_addr,
                num_cpus=node_type.resources.get("CPU"),
                resources={
                    k: v for k, v in node_type.resources.items() if k != "CPU"
                },
                labels={"autoscaler-node-type": node_type.name},
            )
            with self._lock:
                pid = f"fake-{group}-h{h}"
                self._nodes[pid] = {
                    "id": pid,
                    # all hosts of one launch share a group: the slice is
                    # the unit of accounting AND termination
                    "group": group,
                    "node_type": node_type.name,
                    # wall clock: consumed by the autoscaler's boot-grace
                # check, which also uses time.time() — a monotonic stamp
                # compared against wall time would make every boot look
                # ancient and void the booting-supply credit
                "launched_at": time.time(),
                    "proc": proc,
                    "node_id_hex": getattr(proc, "node_id_hex", None),
                }
                ids.append(pid)
        return ids

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(provider_id, None)
        if rec is None:
            return
        # SIGINT first: scale-down is an INTENTIONAL termination, not a
        # preemption — letting the SIGTERM drain protocol run would
        # self-report DRAINING, which the autoscaler counts as unmet
        # demand and replaces (terminate → replace → idle → terminate
        # oscillation). Same teardown-vs-drain split as Cluster.shutdown.
        import os
        import signal

        try:
            os.kill(rec["proc"].pid, signal.SIGINT)
        except OSError:
            pass
        # escalating group reap (util/reaper.py): the daemon AND its
        # workers go down, bounded, even if SIGTERM is ignored
        from ray_tpu.util.reaper import reap_process

        reap_process(rec["proc"], group=True)

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {k: v for k, v in rec.items() if k != "proc"}
                for rec in self._nodes.values()
            ]

    def shutdown(self) -> None:
        for pid in [r["id"] for r in self.non_terminated_nodes()]:
            self.terminate_node(pid)
