"""Simulated multi-node cluster for tests.

Reference: ``python/ray/cluster_utils.py:135`` — the single most
load-bearing test fixture: boots extra node daemons as local processes
with fake resources (``add_node`` ``:201``), kills them (``remove_node``
``:282``), so distributed behavior (spillback scheduling, object
transfer, node failure, PG spread) is testable on one machine.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu.core.cluster_backend import _subprocess_env, spawn_node


class Cluster:
    def __init__(self, head_resources: Optional[Dict[str, float]] = None, num_cpus: float = 1):
        session_dir = f"/tmp/ray_tpu/cluster_{os.getpid()}_{int(time.time()*1000)}"
        os.makedirs(session_dir, exist_ok=True)
        cmd = [
            sys.executable,
            "-m",
            "ray_tpu.core.head_main",
            "--session-dir",
            session_dir,
            "--num-cpus",
            str(num_cpus),
        ]
        if head_resources:
            cmd += ["--resources", json.dumps(head_resources)]
        from ray_tpu.core.config import serialize_config

        cmd += ["--system-config", serialize_config()]
        err_f = open(os.path.join(session_dir, "head.log"), "ab")
        self._head = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=err_f, start_new_session=True,
            env=_subprocess_env(),
        )
        line = self._head.stdout.readline().decode()
        if not line:
            raise RuntimeError(f"cluster head failed (see {session_dir}/head.log)")
        ports = json.loads(line)
        self.controller_port: int = ports["controller_port"]
        self.head_daemon_port: int = ports["daemon_port"]
        self.session_dir = session_dir
        self.nodes: List[subprocess.Popen] = []

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.controller_port}:{self.head_daemon_port}"

    def add_node(
        self,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> subprocess.Popen:
        proc = spawn_node(
            f"127.0.0.1:{self.controller_port}",
            num_cpus=num_cpus,
            resources=resources,
            labels=labels,
        )
        self.nodes.append(proc)
        return proc

    def remove_node(self, proc: subprocess.Popen) -> None:
        """Hard-kill a node (daemon + its workers), like a machine loss."""
        try:
            os.killpg(os.getpgid(proc.pid), 9)
        except Exception:
            proc.kill()
        proc.wait(timeout=10)
        if proc in self.nodes:
            self.nodes.remove(proc)

    def shutdown(self) -> None:
        """Escalating teardown of every process this cluster spawned: one
        shared SIGTERM grace across node groups + the head group, SIGKILL
        survivors (util/reaper.py). Bounded — a SIGTERM-ignoring daemon
        cannot wedge the test that owns this cluster."""
        import signal as _signal

        from ray_tpu.util.reaper import reap_all

        # SIGINT first: driver-initiated teardown means "cluster over",
        # not preemption — node daemons must stop immediately instead of
        # entering the SIGTERM drain protocol (self-report, actor grace,
        # object flush against peers that are dying too)
        for proc in self.nodes:
            try:
                os.kill(proc.pid, _signal.SIGINT)
            except OSError:
                pass
        leaked = reap_all(list(self.nodes) + [self._head], group=True)
        if leaked:
            import logging

            logging.getLogger(__name__).error(
                "cluster shutdown left unreapable pids: %s", leaked
            )
        self.nodes.clear()
