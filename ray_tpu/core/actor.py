"""Actor classes, handles, and methods.

Reference: ``python/ray/actor.py`` — ``ActorClass._remote`` (``:869``)
registers the actor with the control plane and returns a serializable
``ActorHandle``; method calls flow through per-handle ordered submission
(sequence numbers assigned at submit, enforced server-side — reference
``SequentialActorSubmitQueue``). ``@method`` sets per-method options such as
``num_returns`` and ``concurrency_group``.
"""

from __future__ import annotations

import inspect
import threading
from typing import Any, Dict, Optional

from ray_tpu.core.api import _global_worker
from ray_tpu.core.ids import ActorID
from ray_tpu.core.refs import Address, ObjectRef
from ray_tpu.core.task_spec import TaskKind, TaskOptions


def method(**opts):
    """Decorator for actor methods: ``@method(num_returns=2)``."""

    def wrap(fn):
        fn.__ray_tpu_method_opts__ = opts
        return fn

    return wrap


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, opts: Dict[str, Any]):
        self._handle = handle
        self._name = name
        self._opts = opts

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(self._name, args, kwargs, self._opts)

    def options(self, **updates) -> "ActorMethod":
        merged = dict(self._opts)
        merged.update(updates)
        return ActorMethod(self._handle, self._name, merged)

    def bind(self, *args, **kwargs):
        try:
            from ray_tpu.dag.node import ActorMethodNode
        except ImportError as e:
            raise NotImplementedError(
                "ray_tpu.dag (compiled graphs) is not available in this build"
            ) from e

        return ActorMethodNode(self._handle, self._name, args, kwargs, self._opts)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name}() cannot be called directly; use "
            f".{self._name}.remote()"
        )


class ActorHandle:
    """Serializable reference to a running actor.

    Lifetime (reference ``actor.py`` handle semantics): the handle
    returned by ``Cls.remote()`` OWNS an anonymous non-detached actor —
    when it is garbage-collected the actor is terminated, freeing its
    resources. Deserialized copies and ``get_actor`` lookups are borrowed
    and never terminate on drop. NAMED actors are registry-reachable
    (``get_actor``) and therefore exempt, as are ``lifetime="detached"``
    actors — both die only via ``kill``/job end. (The reference refcounts
    every live handle cluster-wide; creator-handle ownership is this
    build's approximation.)"""

    def __init__(
        self,
        actor_id: ActorID,
        method_opts: Dict[str, Dict[str, Any]],
        owner: Optional[Address],
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        owned: bool = False,
        max_concurrency: int = 1,
    ):
        self._actor_id = actor_id
        self._method_opts = method_opts
        self._owner = owner
        self._name = name
        self._namespace = namespace
        self._owned = owned
        # carried in the handle so a BORROWER's first calls dispatch
        # concurrently instead of serializing through the ordered pump
        # until an actor-info round-trip resolves it
        self._max_concurrency = max(1, max_concurrency)
        self._seq_lock = threading.Lock()
        self._seq_no = 0
        # per-method cached task-spec templates (invariant fields spliced
        # with per-call args/seq at submit); False = method not
        # templatable. Rebuilt lazily, never serialized with the handle.
        self._templates: Dict[str, Any] = {}

    def __del__(self):
        if not getattr(self, "_owned", False):
            return
        try:
            from ray_tpu.core.api import get_global_worker_or_none

            w = get_global_worker_or_none()
            if w is None:
                return
            # Graceful out-of-scope termination (reference actor GC):
            # __ray_terminate__ rides the per-actor ORDERED submit queue,
            # so every call submitted before the handle dropped drains
            # first; restarts are disabled via a non-blocking control
            # message. Everything here is fire-and-forget — cyclic GC can
            # run __del__ on any thread (including the io loop), where a
            # blocking RPC wait would deadlock the driver.
            w.backend.mark_actor_no_restart(self._actor_id)
            self._submit_method("__ray_terminate__", (), {}, {})
        except Exception:
            pass  # interpreter teardown / backend already gone

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        opts = self._method_opts.get(name)
        if opts is None:
            raise AttributeError(f"actor has no method {name!r}")
        return ActorMethod(self, name, opts)

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq_no += 1
            return self._seq_no

    def _submit_method(self, method_name: str, args, kwargs, opts: Dict[str, Any]):
        worker = _global_worker()
        # Fast path — cached spec template. Only the DEFAULT method opts
        # (the dict stored in method_opts, handed out by __getattr__) are
        # templatable: an .options() override builds a fresh merged dict,
        # which falls through to the slow path below. Built-in __ray_*
        # methods stay on the slow path — __ray_terminate__ runs from
        # __del__ (possibly ON the io loop), where first-call template
        # registration (a blocking kv_put) could deadlock.
        if not method_name.startswith("__ray_") and (
            opts is self._method_opts.get(method_name) or not opts
        ):
            tmpl = self._templates.get(method_name)
            if tmpl is not False:
                if not worker.template_current(tmpl):
                    topts0 = TaskOptions().merged_with(
                        **{
                            k: v
                            for k, v in opts.items()
                            if k in TaskOptions.__dataclass_fields__
                        }
                    )
                    tmpl = worker.make_spec_template(
                        TaskKind.ACTOR_TASK,
                        None,
                        method_name,
                        topts0,
                        actor_id=self._actor_id,
                        method_name=method_name,
                        default_cpus=0.0,
                        max_concurrency=self._max_concurrency,
                        concurrency_group=opts.get("concurrency_group"),
                    )
                    self._templates[method_name] = tmpl if tmpl is not None else False
                if tmpl:
                    return worker.submit_from_template(
                        tmpl, args, kwargs, seq_no=self._next_seq()
                    )
        topts = TaskOptions().merged_with(
            **{k: v for k, v in opts.items() if k in TaskOptions.__dataclass_fields__}
        )
        spec = worker.make_task_spec(
            TaskKind.ACTOR_TASK,
            None,
            f"{method_name}",
            args,
            kwargs,
            topts,
            actor_id=self._actor_id,
            method_name=method_name,
            default_cpus=0.0,
        )
        spec.seq_no = self._next_seq()
        spec.concurrency_group = opts.get("concurrency_group")
        spec.max_concurrency = self._max_concurrency  # dispatch-path hint
        if spec.num_returns == "streaming":
            # generator method: items stream back over the push connection
            # exactly like normal streaming tasks (task_manager.h:102)
            from ray_tpu.core.streaming import ObjectRefGenerator

            worker.backend.create_stream(spec)
            worker.backend.submit_actor_task(spec)
            return ObjectRefGenerator(
                worker.backend, spec.task_id.binary(), worker.address
            )
        worker.backend.submit_actor_task(spec)
        refs = [ObjectRef(oid, worker.address) for oid in spec.return_ids]
        worker.backend.release_hold(spec.return_ids)
        if spec.num_returns == 0:
            return None
        return refs[0] if spec.num_returns == 1 else refs

    def __ray_ready__(self) -> ObjectRef:
        return self._submit_method("__ray_ready__", (), {}, {})

    def __ray_terminate__(self) -> ObjectRef:
        return self._submit_method("__ray_terminate__", (), {}, {})

    def __reduce__(self):
        # Serializing a handle HANDS THE ACTOR OFF: without distributed
        # handle refcounting, auto-reclaim on creator-handle drop would
        # kill an actor another process is using (factory pattern). A
        # shared actor's lifetime falls back to kill()/job end.
        self._owned = False
        return (
            ActorHandle,
            (
                self._actor_id,
                self._method_opts,
                self._owner,
                self._name,
                self._namespace,
                False,
                self._max_concurrency,
            ),
        )

    def __repr__(self) -> str:
        return f"ActorHandle({self._actor_id.hex()}, name={self._name!r})"


def _collect_method_opts(cls: type) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for name, member in inspect.getmembers(cls, predicate=callable):
        if name.startswith("__") and name not in ("__call__",):
            continue
        out[name] = dict(getattr(member, "__ray_tpu_method_opts__", {}))
    out["__ray_ready__"] = {}
    out["__ray_terminate__"] = {}
    return out


class ActorClass:
    def __init__(self, cls: type, opts: Optional[TaskOptions] = None):
        if not inspect.isclass(cls):
            raise TypeError("@remote on non-class; use RemoteFunction")
        self._cls = cls
        self._opts = opts or TaskOptions()
        self.__name__ = cls.__name__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()"
        )

    def options(self, **updates) -> "ActorClass":
        return ActorClass(self._cls, self._opts.merged_with(**updates))

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = _global_worker()
        opts = self._opts
        if opts.name and opts.get_if_exists:
            try:
                return get_actor(opts.name, opts.namespace)
            except ValueError:
                pass
        actor_id = ActorID.of(worker.job_id)
        spec = worker.make_task_spec(
            TaskKind.ACTOR_CREATION,
            self._cls,
            f"{self._cls.__name__}.__init__",
            args,
            kwargs,
            opts,
            actor_id=actor_id,
            default_cpus=1.0,
        )
        spec.method_opts = _collect_method_opts(self._cls)
        try:
            worker.backend.create_actor(spec)
        except ValueError:
            if opts.name and opts.get_if_exists:
                # lost the name race — someone created it first
                return get_actor(opts.name, opts.namespace)
            raise
        return ActorHandle(
            actor_id,
            spec.method_opts,
            worker.address,
            name=opts.name,
            namespace=opts.namespace or worker.namespace,
            owned=opts.lifetime != "detached" and opts.name is None,
            max_concurrency=opts.max_concurrency or 1,
        )

    def bind(self, *args, **kwargs):
        try:
            from ray_tpu.dag.node import ActorClassNode
        except ImportError as e:
            raise NotImplementedError(
                "ray_tpu.dag (compiled graphs) is not available in this build"
            ) from e

        return ActorClassNode(self, args, kwargs)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    worker = _global_worker()
    info = worker.backend.get_named_actor(name, namespace or worker.namespace)
    if info is None:
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    actor_id, method_opts, owner = info[:3]
    maxc = info[3] if len(info) > 3 else 1
    return ActorHandle(
        actor_id, method_opts, owner, name=name, namespace=namespace,
        max_concurrency=maxc,
    )


def kill(actor_or_handle, *, no_restart: bool = True) -> None:
    if not isinstance(actor_or_handle, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    _global_worker().backend.kill_actor(actor_or_handle.actor_id, no_restart)
