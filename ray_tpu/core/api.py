"""The driver/worker-side runtime core and public API implementation.

Equivalent of the reference's ``python/ray/_private/worker.py``: a global
``Worker`` owns the connection to a runtime backend, performs argument
serialization/inlining on submit, creates return refs (ownership lives with
the submitter — reference ownership model), and implements
get/put/wait/kill/cancel on top of the backend.

Two backends implement ``RuntimeBackend``:
  * ``LocalBackend`` — in-process eager execution (``local_mode``).
  * ``ClusterBackend`` — the real multiprocess runtime (controller + node
    daemons + workers, shared-memory object store).
"""

from __future__ import annotations

import atexit
import contextvars
import os
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.exceptions import GetTimeoutError, TaskError
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu.core.refs import Address, ObjectRef, set_refcount_hooks
from ray_tpu.core.function_manager import FunctionTable, TemplateTable
from ray_tpu.core.task_spec import (
    DefaultScheduling,
    SpecTemplate,
    TaskKind,
    TaskOptions,
    TaskSpec,
)


class RuntimeBackend(ABC):
    """What a runtime must provide to the API layer."""

    @abstractmethod
    def put_object(self, object_id: ObjectID, value: serialization.SerializedValue) -> None: ...

    @abstractmethod
    def get_objects(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]: ...

    @abstractmethod
    def wait(self, refs: Sequence[ObjectRef], num_returns: int, timeout: Optional[float], fetch_local: bool) -> Tuple[List[ObjectRef], List[ObjectRef]]: ...

    @abstractmethod
    def submit_task(self, spec: TaskSpec) -> None: ...

    @abstractmethod
    def create_actor(self, spec: TaskSpec) -> None: ...

    @abstractmethod
    def submit_actor_task(self, spec: TaskSpec) -> None: ...

    @abstractmethod
    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None: ...

    def kill_actor_nowait(self, actor_id: ActorID) -> None:
        """Fire-and-forget kill, safe from GC/finalizer contexts."""
        self.kill_actor(actor_id, True)

    def mark_actor_no_restart(self, actor_id: ActorID) -> None:
        """Disable restarts ahead of a graceful termination (no-op where
        restarts don't exist)."""

    @abstractmethod
    def cancel(self, ref: ObjectRef, force: bool, recursive: bool) -> None: ...

    @abstractmethod
    def get_named_actor(self, name: str, namespace: str) -> Any: ...

    @abstractmethod
    def list_named_actors(self, all_namespaces: bool) -> List[Any]: ...

    @abstractmethod
    def kv_put(self, key: bytes, value: bytes) -> None: ...

    @abstractmethod
    def kv_get(self, key: bytes) -> Optional[bytes]: ...

    def kv_keys(self, prefix: bytes = b"") -> List[bytes]:
        return []

    @abstractmethod
    def free(self, object_ids: Sequence[ObjectID]) -> None: ...

    @abstractmethod
    def add_local_ref(self, ref: ObjectRef) -> None: ...

    @abstractmethod
    def remove_local_ref(self, ref: ObjectRef) -> None: ...

    def register_borrow(self, ref: ObjectRef) -> None:
        """A ref was deserialized into this process (borrower protocol)."""
        self.add_local_ref(ref)

    def release_hold(self, object_ids: Sequence[ObjectID]) -> None:
        """Release the submission hold after real ObjectRefs exist."""

    @abstractmethod
    def cluster_resources(self) -> Dict[str, float]: ...

    @abstractmethod
    def available_resources(self) -> Dict[str, float]: ...

    @abstractmethod
    def nodes(self) -> List[Dict[str, Any]]: ...

    @abstractmethod
    def shutdown(self) -> None: ...


class Worker:
    """Per-process runtime state (driver, worker, or local mode)."""

    MODE_LOCAL = "local"
    MODE_DRIVER = "driver"
    MODE_WORKER = "worker"

    def __init__(self, mode: str, backend: RuntimeBackend, job_id: JobID, namespace: str):
        self.mode = mode
        self.backend = backend
        self.job_id = job_id
        self.namespace = namespace
        self.worker_id = WorkerID.from_random()
        self.address: Optional[Address] = None  # set by cluster runtime
        self._put_counter = 0
        self._task_counter = 0
        self._packaged_envs: Dict[Any, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self.fn_table = FunctionTable(backend.kv_put, backend.kv_get)
        self.tmpl_table = TemplateTable(backend.kv_put)
        # Pre-warm the None-function export (actor-method specs carry
        # function_obj=None; its FIRST export does a blocking kv_put).
        # Without this, the first slow-path actor submit in a process can
        # be ActorHandle.__del__'s __ray_terminate__ — and cyclic GC can
        # run that __del__ ON the io-loop thread (any allocation there can
        # trigger it), where a blocking io.run() deadlocks the driver: the
        # loop waits on a future only the loop itself could resolve.
        try:
            self.fn_table.export(None)
        except Exception:
            pass  # backend not reachable yet: __del__'s own guard remains
        set_refcount_hooks(self._on_ref_created, self._on_ref_deleted, self._on_ref_borrowed)

    # ---- task context --------------------------------------------------
    # A ContextVar (not threading.local) so the context is correct both on
    # lane threads AND per-coroutine on the async-actor lane — each asyncio
    # task carries its own copy, so concurrent async methods can't cross
    # puts into each other's ObjectID namespace. Entries are job-scoped:
    # a cached driver TaskID from a previous init()/shutdown() cycle (the
    # ContextVar is module-level and outlives the Worker) must not leak
    # into a new job's ObjectID namespace.
    @property
    def current_task_id(self) -> TaskID:
        entry = _current_task_id.get()
        # Auto-created driver entries are invalidated when the job changed
        # (a module-level ContextVar outlives init()/shutdown() cycles);
        # executor-set entries carry their own job and are always valid —
        # the shared self.job_id attr must not leak across concurrent tasks.
        if entry is None or (entry[2] and entry[0] != self.job_id):
            tid = TaskID.for_driver(self.job_id)
            _current_task_id.set((self.job_id, tid, True))
            return tid
        return entry[1]

    def set_task_context(self, task_id: TaskID, job_id: Optional[JobID] = None) -> None:
        _current_task_id.set((job_id or self.job_id, task_id, False))

    # ---- refcounting hooks --------------------------------------------
    def _on_ref_created(self, ref: ObjectRef) -> None:
        try:
            self.backend.add_local_ref(ref)
        except Exception:
            pass

    def _on_ref_deleted(self, ref: ObjectRef) -> None:
        try:
            self.backend.remove_local_ref(ref)
        except Exception:
            pass

    def _on_ref_borrowed(self, ref: ObjectRef) -> None:
        try:
            self.backend.register_borrow(ref)
        except Exception:
            pass

    # ---- object API ----------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("calling put on an ObjectRef is not allowed")
        with self._lock:
            self._put_counter += 1
            idx = self._put_counter
        object_id = ObjectID.for_put(self.current_task_id, idx)
        ser = serialization.serialize(value)
        self.backend.put_object(object_id, ser)
        ref = ObjectRef(object_id, self.address)
        self.backend.release_hold([object_id])
        return ref

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        if not all(isinstance(r, ObjectRef) for r in refs):
            raise TypeError("get() expects an ObjectRef or a list of ObjectRefs")
        # ambient Deadline (core/deadline.py): the tighter of the explicit
        # timeout and the caller's remaining budget wins — a timeout=None
        # get inside a deadline scope cannot park past the budget
        from ray_tpu.core.deadline import effective_timeout

        values = self.backend.get_objects(refs, effective_timeout(timeout))
        out = []
        for v in values:
            if isinstance(v, Exception):
                raise v
            out.append(v)
        return out[0] if single else out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        if isinstance(refs, ObjectRef):
            raise TypeError("wait() expects a list of ObjectRefs")
        if len(set(refs)) != len(refs):
            raise ValueError("wait() got duplicate ObjectRefs")
        if num_returns <= 0 or num_returns > len(refs):
            raise ValueError(f"num_returns must be in [1, {len(refs)}]")
        from ray_tpu.core.deadline import effective_timeout

        return self.backend.wait(
            list(refs), num_returns, effective_timeout(timeout), fetch_local
        )

    # ---- task submission ----------------------------------------------
    def _serialize_args(self, args, kwargs):
        """Inline small args; implicit-put large ones (reference
        DependencyResolver inlining)."""
        threshold = GLOBAL_CONFIG.max_direct_call_object_size
        sargs = []
        for a in args:
            if isinstance(a, ObjectRef):
                sargs.append(("ref", a))
                continue
            if callable(a):
                serialization.ensure_importable_or_by_value(a)
            ser = serialization.serialize(a)
            if ser.total_bytes <= threshold and not ser.contained_refs:
                sargs.append(("val", ser.to_bytes()))
            else:
                ref = self._put_serialized(ser)
                sargs.append(("ref", ref))
        skwargs = []
        for k, a in (kwargs or {}).items():
            if isinstance(a, ObjectRef):
                skwargs.append(("ref", k, a))
                continue
            if callable(a):
                serialization.ensure_importable_or_by_value(a)
            ser = serialization.serialize(a)
            if ser.total_bytes <= threshold and not ser.contained_refs:
                skwargs.append(("val", k, ser.to_bytes()))
            else:
                ref = self._put_serialized(ser)
                skwargs.append(("ref", k, ref))
        return sargs, skwargs

    def _put_serialized(self, ser: serialization.SerializedValue) -> ObjectRef:
        with self._lock:
            self._put_counter += 1
            idx = self._put_counter
        object_id = ObjectID.for_put(self.current_task_id, idx)
        self.backend.put_object(object_id, ser)
        ref = ObjectRef(object_id, self.address)
        self.backend.release_hold([object_id])
        return ref

    def new_task_id(self) -> TaskID:
        return TaskID.for_task(ActorID.nil_for_job(self.job_id))

    def make_task_spec(
        self,
        kind: TaskKind,
        function_obj: Any,
        name: str,
        args,
        kwargs,
        opts: TaskOptions,
        *,
        actor_id: Optional[ActorID] = None,
        method_name: Optional[str] = None,
        default_cpus: float = 1.0,
    ) -> TaskSpec:
        function_id = self.fn_table.export(function_obj)
        task_id = self.new_task_id()
        sargs, skwargs = self._serialize_args(args, kwargs)
        num_returns = opts.num_returns if opts.num_returns is not None else 1
        if isinstance(num_returns, int):
            return_ids = [ObjectID.from_index(task_id, i + 1) for i in range(num_returns)]
        elif num_returns == "streaming":
            if kind not in (TaskKind.NORMAL, TaskKind.ACTOR_TASK):
                raise ValueError(
                    'num_returns="streaming" is only supported on tasks '
                    "and actor methods"
                )
            return_ids = []  # item ids are generated as the task yields
        else:
            return_ids = [ObjectID.from_index(task_id, 1)]
        max_retries = (
            opts.max_retries
            if opts.max_retries is not None
            else (GLOBAL_CONFIG.task_max_retries if kind == TaskKind.NORMAL else 0)
        )
        runtime_env = opts.runtime_env
        if runtime_env:
            from ray_tpu.runtime_env import validate_runtime_env

            validate_runtime_env(runtime_env)  # fail at submit, not on-worker
        if runtime_env and any(
            k in runtime_env for k in ("working_dir", "py_modules")
        ):
            # ship code at submission: zip -> content-addressed KV upload;
            # workers extract per hash (runtime_env/packaging.py). The
            # driver-side cache keys on path + a tree mtime/size signature
            # so EDITING the directory re-ships it (path-only keying
            # would silently pin the first upload for the driver's life).
            from ray_tpu.runtime_env import package_runtime_env

            key = tuple(sorted(
                (k, str(v), _tree_signature(v))
                for k, v in runtime_env.items()
            ))
            packaged = self._packaged_envs.get(key)
            if packaged is None:
                packaged = package_runtime_env(
                    runtime_env, self.backend.kv_put, self.backend.kv_get
                )
                self._packaged_envs[key] = packaged
            runtime_env = packaged
        if num_returns == "streaming":
            # re-executing a partially-consumed stream has replay
            # semantics this build doesn't implement — no retries
            max_retries = 0
        from ray_tpu.core.deadline import remaining as _deadline_remaining

        return TaskSpec(
            kind=kind,
            task_id=task_id,
            job_id=self.job_id,
            name=name,
            function_id=function_id,
            args=sargs,
            kwargs=skwargs,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=opts.resource_request(default_cpus).to_dict(),
            scheduling_strategy=opts.scheduling_strategy,
            owner=self.address,
            max_retries=max_retries,
            retry_exceptions=opts.retry_exceptions,
            runtime_env=runtime_env,
            deadline_remaining_s=_deadline_remaining(),
            actor_id=actor_id,
            max_restarts=opts.max_restarts,
            max_task_retries=opts.max_task_retries,
            max_concurrency=opts.max_concurrency or 1,
            concurrency_groups=dict(opts.concurrency_groups),
            actor_name=opts.name if kind == TaskKind.ACTOR_CREATION else None,
            namespace=opts.namespace or self.namespace,
            lifetime=opts.lifetime,
            method_name=method_name,
        )

    # ---- cached task-spec templates (submit fast path) -----------------
    def make_spec_template(
        self,
        kind: TaskKind,
        function_obj: Any,
        name: str,
        opts: TaskOptions,
        *,
        actor_id: Optional[ActorID] = None,
        method_name: Optional[str] = None,
        default_cpus: float = 1.0,
        max_concurrency: int = 1,
        concurrency_group: Optional[str] = None,
    ) -> Optional[SpecTemplate]:
        """Capture the invariant spec fields of one remote function /
        actor method ONCE (reference: cached serialized task-spec
        prefix). Returns None for shapes the fast path doesn't cover
        (streaming/dynamic returns, runtime_env — its packaging is
        re-signatured per submit)."""
        num_returns = opts.num_returns if opts.num_returns is not None else 1
        if not isinstance(num_returns, int) or opts.runtime_env:
            return None
        max_retries = (
            opts.max_retries
            if opts.max_retries is not None
            else (GLOBAL_CONFIG.task_max_retries if kind == TaskKind.NORMAL else 0)
        )
        return self.tmpl_table.register(
            dict(
                kind=kind,
                name=name,
                function_id=self.fn_table.export(function_obj),
                num_returns=num_returns,
                resources=opts.resource_request(default_cpus).to_dict(),
                scheduling_strategy=opts.scheduling_strategy,
                owner=self.address,
                job_id=self.job_id,
                max_retries=max_retries,
                retry_exceptions=opts.retry_exceptions,
                runtime_env=None,
                actor_id=actor_id,
                method_name=method_name,
                max_concurrency=max_concurrency,
                concurrency_group=concurrency_group,
            )
        )

    def template_current(self, tmpl: Optional[SpecTemplate]) -> bool:
        """A cached template is reusable only while its captured process
        identity holds (job and owner address change across init cycles
        and across tasks on a reused worker)."""
        return (
            tmpl is not None
            and tmpl.job_id == self.job_id
            and tmpl.owner is self.address
        )

    def submit_from_template(self, tmpl: SpecTemplate, args, kwargs, seq_no: int = 0):
        """Hot-path submit: splice per-call fields into a cached template
        — no TaskOptions merging, resource normalization, or descriptor
        re-export per call."""
        from ray_tpu.core.deadline import remaining as _deadline_remaining

        task_id = self.new_task_id()
        sargs, skwargs = self._serialize_args(args, kwargs)
        return_ids = [
            ObjectID.from_index(task_id, i + 1) for i in range(tmpl.num_returns)
        ]
        spec = tmpl.instantiate(
            task_id, sargs, skwargs, return_ids, _deadline_remaining(), seq_no
        )
        if tmpl.kind == TaskKind.ACTOR_TASK:
            self.backend.submit_actor_task(spec)
        else:
            self.backend.submit_task(spec)
        refs = [ObjectRef(oid, self.address) for oid in spec.return_ids]
        self.backend.release_hold(spec.return_ids)
        if tmpl.num_returns == 0:
            return None
        return refs[0] if tmpl.num_returns == 1 else refs

    def submit_task(self, function_obj, name, args, kwargs, opts: TaskOptions):
        spec = self.make_task_spec(TaskKind.NORMAL, function_obj, name, args, kwargs, opts)
        if spec.num_returns == "streaming":
            from ray_tpu.core.streaming import ObjectRefGenerator

            self.backend.create_stream(spec)
            self.backend.submit_task(spec)
            return ObjectRefGenerator(
                self.backend, spec.task_id.binary(), self.address
            )
        self.backend.submit_task(spec)
        refs = [ObjectRef(oid, self.address) for oid in spec.return_ids]
        self.backend.release_hold(spec.return_ids)
        if spec.num_returns == 0:
            return None
        if spec.num_returns == 1:
            return refs[0]
        return refs

    # ---- futures -------------------------------------------------------
    def to_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    async def await_ref(self, ref: ObjectRef):
        import asyncio

        return await asyncio.wrap_future(self.to_future(ref))

    def shutdown(self) -> None:
        set_refcount_hooks(None, None, None)
        self.backend.shutdown()


_current_task_id: contextvars.ContextVar[Optional[Tuple[JobID, TaskID]]] = (
    contextvars.ContextVar("ray_tpu_current_task_id", default=None)
)


# --- global worker singleton -------------------------------------------

_worker: Optional[Worker] = None
_worker_lock = threading.Lock()


def _global_worker() -> Worker:
    if _worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _worker


def get_global_worker_or_none() -> Optional[Worker]:
    return _worker


def is_initialized() -> bool:
    return _worker is not None


def set_global_worker(worker: Optional[Worker]) -> None:
    global _worker
    _worker = worker


#: (path -> (computed_at_monotonic, sig)) — every ``.remote()`` carrying
#: a working_dir/py_modules runtime_env asks for the tree signature; a
#: stat-walk of the whole directory per SUBMIT is the dominant cost of
#: runtime_env task loops. Within the TTL the cached signature answers
#: instead; an edit is still re-shipped at most ``tree_signature_ttl_s``
#: late (the reference accepts the same staleness in its working_dir
#: upload cache). TTL 0 disables caching (tests / paranoid callers).
_tree_sig_cache: Dict[str, Tuple[float, int]] = {}


def _tree_signature(value) -> int:
    """Cheap change signature for runtime_env path values: hash of every
    file's (relpath, mtime_ns, size), cached per path for a short TTL.
    Non-path values signature as 0."""
    paths = value if isinstance(value, (list, tuple)) else [value]
    ttl = GLOBAL_CONFIG.tree_signature_ttl_s
    now = time.monotonic()
    sig = 0
    for p in paths:
        if not isinstance(p, str) or not os.path.exists(p):
            continue
        if ttl > 0:
            cached = _tree_sig_cache.get(p)
            if cached is not None and now - cached[0] < ttl:
                sig = hash((sig, cached[1]))
                continue
        psig = _stat_walk_signature(p)
        if ttl > 0:
            _tree_sig_cache[p] = (now, psig)
        sig = hash((sig, psig))
    return sig


def _stat_walk_signature(p: str) -> int:
    sig = 0
    if os.path.isfile(p):
        st = os.stat(p)
        return hash((p, st.st_mtime_ns, st.st_size))
    for root, dirs, files in os.walk(p):
        dirs.sort()
        for f in sorted(files):
            try:
                st = os.stat(os.path.join(root, f))
            except OSError:
                continue
            sig = hash((sig, os.path.join(root, f), st.st_mtime_ns, st.st_size))
    return sig


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    local_mode: bool = False,
    namespace: Optional[str] = None,
    object_store_memory: Optional[int] = None,
    system_config: Optional[Dict[str, Any]] = None,
    num_nodes: int = 1,
    ignore_reinit_error: bool = False,
) -> Dict[str, Any]:
    """Start (or connect to) a runtime. Returns context info.

    Reference: ``ray.init`` (``python/ray/_private/worker.py:1262``).
    With no ``address`` a local cluster is started in-process
    (controller + node daemon + workers); ``local_mode=True`` executes
    everything eagerly in the driver process.
    """
    global _worker
    if address is None:
        # job entrypoints get the cluster address injected by their
        # supervisor (reference: RAY_ADDRESS; job/supervisor.py)
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    with _worker_lock:
        if _worker is not None:
            if ignore_reinit_error:
                return {"namespace": _worker.namespace}
            raise RuntimeError("ray_tpu.init() called twice")
        if system_config:
            GLOBAL_CONFIG.apply_system_config(system_config)
        if object_store_memory:
            GLOBAL_CONFIG.object_store_memory_bytes = object_store_memory
        import uuid

        ns = namespace or uuid.uuid4().hex[:12]
        job_id = JobID.from_random()
        if local_mode:
            from ray_tpu.core.local_backend import LocalBackend

            backend = LocalBackend(num_cpus=num_cpus or 8, resources=resources)
            _worker = Worker(Worker.MODE_LOCAL, backend, job_id, ns)
            backend.bind_worker(_worker)
        elif address is None:
            from ray_tpu.core.cluster_backend import ClusterBackend

            backend = ClusterBackend.start_cluster(
                num_cpus=num_cpus, resources=resources, num_nodes=num_nodes
            )
            _worker = Worker(Worker.MODE_DRIVER, backend, job_id, ns)
            backend.bind_worker(_worker)
        else:
            from ray_tpu.core.cluster_backend import ClusterBackend

            backend = ClusterBackend.connect(address)
            _worker = Worker(Worker.MODE_DRIVER, backend, job_id, ns)
            backend.bind_worker(_worker)
        atexit.register(shutdown)
        return {"namespace": ns, "job_id": job_id.hex()}


def shutdown() -> None:
    global _worker
    with _worker_lock:
        if _worker is None:
            return
        try:
            _worker.shutdown()
        finally:
            _worker = None
