"""Driver-side cluster backend: CoreWorker + cluster lifecycle.

``ray_tpu.init()`` with no address spawns a head process (controller +
head-node daemon, see ``head_main.py``) and connects to it;
``ray_tpu.init(address=...)`` connects to an existing cluster started by
the ``Cluster`` test fixture or the CLI. Address format:
``host:controller_port:daemon_port``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional

from ray_tpu.core.core_worker import CoreWorker


def _subprocess_env() -> dict:
    """Env for child processes: make the ray_tpu package importable even
    when the driver found it via sys.path manipulation, and strip env
    triggers that would start per-process accelerator tunnel clients in
    pure control-plane daemons (see ``GlobalConfig.strip_child_env``)."""
    import ray_tpu
    from ray_tpu.core.config import scrub_child_env

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    env = scrub_child_env(dict(os.environ))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = pkg_root + (os.pathsep + existing if existing else "")
    # every process spawned through here belongs to THIS driver: it must
    # exit (gracefully) if the driver dies without running shutdown —
    # the orphaned-head_main leak class (util/reaper.start_orphan_watch)
    from ray_tpu.util.reaper import EXIT_ON_DRIVER_EXIT_ENV, SPAWNER_PID_ENV

    env[EXIT_ON_DRIVER_EXIT_ENV] = "1"
    env[SPAWNER_PID_ENV] = str(os.getpid())
    # cluster-wide trace epoch: every runtime process mints trace ids
    # under the driver's epoch prefix, so ids from one cluster
    # incarnation never collide with a restarted one's (tracing.py)
    from ray_tpu.observability.tracing import TRACE_EPOCH_ENV, trace_epoch

    env.setdefault(TRACE_EPOCH_ENV, trace_epoch())
    return env


def _spawn_and_handshake(cmd, log_path: str, what: str) -> tuple:
    """Spawn one runtime process (head / node daemon / standalone
    controller) and complete the stdout handshake: every spawner shares
    the same contract — detached session + driver-scoped env
    (``_subprocess_env``: orphan watch, scrubbed accelerator triggers,
    the cluster trace epoch), stderr appended to ``log_path``, and ONE
    stdout line of JSON announcing the ports. Returns ``(proc, info)``.
    (Third and last of the PR 5 deferred refactor trio: spawn_node and
    spawn_controller used to duplicate all of this.)"""
    os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
    err_f = open(log_path, "ab")
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=err_f, start_new_session=True,
        env=_subprocess_env(),
    )
    line = proc.stdout.readline().decode()
    if not line:
        raise RuntimeError(f"{what} failed to start (see {log_path})")
    return proc, json.loads(line)


class ClusterBackend(CoreWorker):
    _head_proc: Optional[subprocess.Popen] = None

    @classmethod
    def start_cluster(
        cls,
        num_cpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        num_nodes: int = 1,
    ) -> "ClusterBackend":
        session_dir = f"/tmp/ray_tpu/session_{os.getpid()}_{int(time.time())}"
        cmd = [sys.executable, "-m", "ray_tpu.core.head_main", "--session-dir", session_dir]
        if num_cpus is not None:
            cmd += ["--num-cpus", str(num_cpus)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        from ray_tpu.core.config import GLOBAL_CONFIG, serialize_config

        cmd += ["--system-config", serialize_config()]
        os.makedirs(session_dir, exist_ok=True)
        proc, ports = _spawn_and_handshake(
            cmd, os.path.join(session_dir, "head.log"), "head process"
        )
        backend = cls(
            "127.0.0.1", ports["controller_port"], "127.0.0.1", ports["daemon_port"]
        )
        backend._head_proc = proc
        backend._finish_handshake()
        # extra simulated nodes (tests / local multi-node)
        backend._extra_nodes = []
        for _ in range(max(0, num_nodes - 1)):
            backend._extra_nodes.append(
                spawn_node(
                    f"127.0.0.1:{ports['controller_port']}", num_cpus=num_cpus, resources=resources
                )
            )
        return backend

    @classmethod
    def connect(cls, address: str) -> "ClusterBackend":
        host, cport, dport = address.rsplit(":", 2)
        backend = cls(host, int(cport), host, int(dport))
        backend._head_proc = None
        backend._extra_nodes = []
        backend._finish_handshake()
        return backend

    def _finish_handshake(self) -> None:
        reply = self.io.run(self.daemon.call("hello", retries=5))
        self.finish_init(reply["node_id"])

    def bind_worker(self, worker) -> None:
        worker.address = self.address
        self.io.run(
            self.controller.call(
                "register_job", {"job_id": worker.job_id.binary(), "driver_pid": os.getpid()}
            )
        )

    def shutdown(self) -> None:
        super().shutdown()
        for proc in getattr(self, "_extra_nodes", []):
            _stop(proc)
        if self._head_proc is not None:
            _stop(self._head_proc)


def spawn_node(
    controller_addr: str,
    num_cpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "ray_tpu.core.node_main", "--controller", controller_addr]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    if labels:
        cmd += ["--labels", json.dumps(labels)]
    from ray_tpu.core.config import serialize_config

    cmd += ["--system-config", serialize_config()]
    proc, info = _spawn_and_handshake(
        cmd,
        f"/tmp/ray_tpu/node-{os.getpid()}-{time.time_ns()}.log",
        "node daemon",
    )
    proc.node_port = info["daemon_port"]  # type: ignore[attr-defined]
    proc.node_id_hex = info["node_id"]  # type: ignore[attr-defined]
    return proc


def spawn_controller(
    session_dir: str, port: int = 0, standby: bool = False
) -> subprocess.Popen:
    """Spawn a STANDALONE controller process (``controller_main.py``) —
    the failover topology where the control plane can be killed and
    restarted from its snapshot + WAL independently of every node
    daemon. Restarting with the same ``session_dir`` restores state AND
    the old listening port, so clients reconnect with no rediscovery.
    The returned proc carries ``controller_port``.

    ``standby=True`` starts a HOT STANDBY follower instead: it tails the
    session WAL and the active's lease file, and promotes itself (WAL
    replay to the tip, epoch bump, same-port rebind) the moment the
    lease goes stale or is released. Its ``controller_port`` is the
    port the ACTIVE held at spawn time — the address the promoted
    standby will rebind."""
    from ray_tpu.core.config import serialize_config

    os.makedirs(session_dir, exist_ok=True)
    cmd = [
        sys.executable, "-m", "ray_tpu.core.controller_main",
        "--session-dir", session_dir, "--port", str(port),
        "--system-config", serialize_config(),
    ]
    log_name = "controller-standby.log" if standby else "controller.log"
    if standby:
        cmd.append("--standby")
    proc, info = _spawn_and_handshake(
        cmd, os.path.join(session_dir, log_name), "controller"
    )
    proc.controller_port = info["controller_port"]  # type: ignore[attr-defined]
    proc.standby = bool(info.get("standby", False))  # type: ignore[attr-defined]
    return proc


def _stop(proc: subprocess.Popen) -> None:
    """Escalating stop of a spawned runtime process AND its process group
    (head/node daemons run with start_new_session=True and own their
    workers' group): SIGTERM → grace → SIGKILL, always bounded. The group
    kill is what prevents the round-5 "orphaned head_main" leak class —
    terminating only the leader leaves its children reparented to init.

    SIGINT precedes the reap: driver-initiated teardown is "cluster
    over", not a preemption warning — daemons must exit now, not run the
    SIGTERM drain protocol."""
    import signal

    from ray_tpu.util.reaper import reap_process

    try:
        os.kill(proc.pid, signal.SIGINT)
    except OSError:
        pass
    reap_process(proc, group=True)
