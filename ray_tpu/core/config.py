"""Typed runtime configuration flags.

Equivalent of the reference's RayConfig flag system
(``src/ray/common/ray_config_def.h:18-22``): every flag has a type and a
default, is overridable per-process via ``RAY_TPU_<name>`` environment
variables, and cluster-wide via a ``system_config`` dict handed to
``ray_tpu.init``. Flags are plain attributes on the singleton ``GlobalConfig``
so hot paths read them without dict lookups.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class GlobalConfig:
    # --- object store ---
    object_store_memory_bytes: int = 2 * 1024**3
    # Objects at or below this size are stored inline in the owner's
    # in-process memory store and shipped inside RPC replies instead of
    # going through shared memory (reference: task output inlining).
    max_direct_call_object_size: int = 100 * 1024
    # Task RESULTS at or below this size ride back to the owner inside
    # the task-done reply and are served from the owner's in-process
    # inline cache — get() on a small result never touches the shm store
    # or makes an extra RPC (reference: direct-call inline return limit).
    # Distinct from max_direct_call_object_size (puts / arg inlining) so
    # the two paths can be tuned independently.
    inline_result_threshold_bytes: int = 100 * 1024
    # Chunk size for node-to-node object transfer (reference 5 MiB,
    # ``ray_config_def.h:341``).
    object_transfer_chunk_bytes: int = 5 * 1024**2
    # Spill to disk when the store is above this fraction of capacity.
    object_spilling_threshold: float = 0.8
    object_spilling_dir: str = ""
    # Per-process cap on the segment reuse pool (plasma-arena-style warm
    # page recycling in StoreClient; 0 disables recycling).
    object_store_recycle_bytes: int = 512 * 1024**2

    # --- pull manager (core/pull_manager.py: daemon↔daemon transfer) ---
    #: admission budget for concurrent inbound transfers: total bytes of
    #: objects in flight; further pulls queue FIFO (backpressure instead
    #: of OOMing the daemon). An object larger than the whole budget is
    #: still admitted when it is alone. <=0 disables admission control.
    pull_max_inflight_bytes: int = 256 * 1024**2
    #: per-chunk fetch timeout — a stalled source costs one chunk
    #: timeout, not the whole-transfer timeout
    pull_chunk_timeout_s: float = 15.0
    #: chunk fetch attempts per source before failing over to the next
    #: source (the transfer RESUMES from the last verified offset there)
    pull_chunk_retries: int = 3
    #: chunk requests kept in flight per transfer (reference: pipelined
    #: 5 MiB chunks) — serial request/response is latency-bound on
    #: virtualized hosts; verification and shm writes stay strictly
    #: sequential regardless. 1 disables pipelining.
    pull_pipeline_depth: int = 4
    #: daemon-side receive-segment reuse pool cap (bytes): segments of
    #: transfer-received objects deleted with ``recycle_receive`` (and
    #: aborted receives this store created) are renamed into a warm
    #: LRU pool instead of unlinked, and ``allocate_receive`` reuses a
    #: fitting one — repeated KV migrations skip segment create/zero
    #: (this 4.4-kernel sandbox can't MADV_POPULATE; warm inodes are
    #: the substitute). 0 disables the pool.
    receive_segment_pool_bytes: int = 128 * 1024**2

    # --- scheduling ---
    # Hybrid policy: prefer local node until it exceeds this utilization
    # fraction, then spread over the top-k best nodes (reference
    # ``hybrid_scheduling_policy.h:50``).
    scheduler_spread_threshold: float = 0.5
    scheduler_top_k_fraction: float = 0.2
    worker_lease_timeout_s: float = 30.0
    # An infeasible-NOW lease parks this long daemon-side before the
    # infeasible verdict is returned: parked demand is what the
    # autoscaler sees, and a joining node can make the shape feasible
    # (reference: infeasible tasks wait forever and feed the load
    # report).
    infeasible_lease_grace_s: float = 10.0
    # The CLIENT keeps retrying an infeasible verdict this long before
    # failing the task — covers node boot time on autoscaled clusters
    # (raise it when provisioning takes minutes) while keeping a crisp
    # terminal error for static ones.
    infeasible_fail_after_s: float = 30.0
    # Release a blocked worker's CPU share back to the node pool while it
    # parks in a sync get/arg-fetch, re-acquiring on wake (reference:
    # NotifyDirectCallTaskBlocked). Without it, a task graph whose
    # consumers saturate every CPU while blocked on producers that still
    # need a CPU deadlocks — the documented fault-recovery trap.
    blocked_worker_resource_release: bool = True
    # Max workers the pool will cold-start concurrently (startup tokens).
    worker_maximum_startup_concurrency: int = 4
    idle_worker_killing_time_s: float = 300.0
    num_initial_workers: int = 0

    # --- streaming generators ---
    #: producer pauses once (produced - consumed) reaches this many
    #: items; consumer progress resumes it (reference ObjectRefStream
    #: consumer-position protocol, ``task_manager.h:102``). 0 disables.
    streaming_generator_backpressure_items: int = 64
    #: inline stream items at or above this size ride a RAW push frame
    #: (core/rpc.py kind 5): the item bytes travel out-of-band instead of
    #: being pickled+msgpacked into the push payload on both ends. Small
    #: items stay on the plain path (a RAW frame costs an extra header).
    #: <0 disables RAW stream pushes entirely.
    rpc_raw_stream_min_bytes: int = 8 * 1024

    # --- fault tolerance ---
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    lineage_pinning_enabled: bool = True
    #: resubmission attempts per lost object (``task_manager.h:273``)
    max_lineage_reconstructions: int = 3
    #: concurrent worker leases per scheduling class (lease pipelining,
    #: ``normal_task_submitter.cc:351``)
    max_lease_pumps: int = 16
    #: how long an idle held lease waits for more same-class work before
    #: being returned
    lease_linger_s: float = 0.02
    #: specs per push RPC on a held lease (serial worker-side execution);
    #: the adaptive divisor in _drain_on_lease shrinks batches once pumps
    #: fan out, so this is the micro-task amortization ceiling
    lease_push_batch: int = 32
    #: a pump spawns a sibling when its push has been in flight this long
    #: with work still queued (demand-adaptive lease pipelining: micro
    #: tasks amortize on one lease; long/blocked tasks fan out to more
    #: workers). Must sit well above micro-task push round-trips even on
    #: a contended box, or noop floods cascade into eager fan-out.
    lease_pump_growth_s: float = 0.05

    # --- observability ---
    #: serve a Prometheus /metrics endpoint from daemons + controller
    metrics_export_enabled: bool = True
    #: fixed metrics port (0 = auto-assign per process)
    metrics_port: int = 0
    #: bind address for /metrics ("0.0.0.0" for off-host Prometheus)
    metrics_bind_host: str = "127.0.0.1"
    #: tail worker logs and forward them to connected drivers
    log_to_driver: bool = True
    #: push task lifecycle events to the controller (state API `list tasks`)
    task_events_enabled: bool = True
    #: distributed-tracing sample rate in [0, 1]: a fresh trace root is
    #: sampled at request entry points (driver submit, serve router
    #: dispatch) with this probability; children inherit the verdict
    #: causally. 0 (default) keeps the submit hot path span-free — one
    #: contextvar read + one float compare per submit, no allocation.
    trace_sample_rate: float = 0.0
    #: byte budget for worker-exported timeline event chunks retained on
    #: the controller (observability/timeline.py): past it the OLDEST
    #: exports are dropped; a dead node's chunks are reaped with it.
    timeline_kv_max_bytes: int = 16 * 1024**2
    #: grace window for daemons to re-register/sync after a controller
    #: restart before unadopted restored state is rescheduled
    controller_restore_grace_s: float = 10.0
    #: controller snapshot (WAL compaction) period; mutations acked
    #: between ticks are covered by the WAL, so raising this trades
    #: replay length for snapshot churn, never durability
    controller_persist_interval_s: float = 1.0
    #: controller WAL fsync policy: fsync every N appended records
    #: (1 = every record, the zero-loss default); 0 = flush to the OS
    #: only (process-crash safe, not host-crash safe). See core/wal.py.
    controller_wal_fsync: int = 1
    #: active controller lease heartbeat period (core/wal.py lease file;
    #: a hot standby polls the same file at this period)
    controller_lease_interval_s: float = 0.5
    #: lease staleness bound: a standby takes over when the lease stamp
    #: is older than this; the ACTIVE self-fences acks at ~75% of it
    #: (stops acking mutations strictly before a standby can assume the
    #: lease is dead — the classic lease safety margin)
    controller_lease_timeout_s: float = 2.0

    # --- SLO ledger (observability/slo.py) ---
    #: flight-recorder slowest-K slots per process (fixed-size heap of
    #: the slowest requests by e2e, TTFT when the request never
    #: streamed). 0 keeps only flagged entries.
    slo_flight_recorder_slots: int = 32
    #: flight-recorder ring capacity for FLAGGED requests (SLO-violating,
    #: resumed, preempted, shed, failed) — newest win
    slo_flight_flagged_slots: int = 128
    #: TTFT above this flags a request into the flight recorder (and the
    #: traffic simulator's default TTFT SLO target)
    slo_ttft_slow_s: float = 2.0
    #: max inter-token gap above this flags a request (ITL SLO target)
    slo_itl_slow_s: float = 1.0

    # --- memory monitor (``common/memory_monitor.h:52``) ---
    memory_monitor_enabled: bool = True
    #: kill the newest leased task worker when the node's available
    #: memory falls below this fraction (owners resubmit per max_retries)
    memory_monitor_min_available_fraction: float = 0.03
    memory_monitor_period_s: float = 1.0

    # --- process environment ---
    #: comma-separated env vars STRIPPED from spawned runtime processes
    #: (control-plane daemons, CPU workers, shm resource trackers). The
    #: default strips the axon TPU-tunnel trigger: when set, this host's
    #: sitecustomize registers a PJRT tunnel client in EVERY python
    #: process, which burns ~half a core per process polling the relay —
    #: daemons and CPU-only workers must not pay that tax. Workers that
    #: are ASSIGNED TPU chips keep their env untouched. Set
    #: RAY_TPU_strip_child_env="" to disable.
    strip_child_env: str = "PALLAS_AXON_POOL_IPS"

    # --- hang defense (observability/event_stats.py, util/reaper.py) ---
    #: instrument owned asyncio loops with a heartbeat + stall watchdog
    event_loop_monitor_enabled: bool = True
    #: heartbeat period; also the watchdog's check interval
    event_loop_tick_s: float = 0.1
    #: heartbeat silence that counts as a stall (dump + stall counter).
    #: The loop-lag gauge is exported regardless; this only gates dumps.
    event_loop_stall_threshold_s: float = 5.0
    #: rate limit between stack dumps while a stall persists
    event_loop_stall_dump_interval_s: float = 30.0
    #: >0: a stall persisting this long HARD-EXITS the process (code 70).
    #: Off by default — production stalls should dump and recover; tests
    #: set it so a wedged process dies visibly instead of freezing pytest.
    watchdog_abort_after_s: float = 0.0
    #: escalating reap: SIGTERM grace before SIGKILL, then SIGKILL grace
    reap_term_grace_s: float = 2.0
    reap_kill_grace_s: float = 3.0

    # --- node drain / preemption (core/node_daemon.py, controller) ---
    #: how long a draining node lets running tasks finish (and library
    #: controllers migrate actors) before it flushes objects and exits
    drain_grace_s: float = 30.0
    #: treat SIGTERM to a worker-node daemon as a preemption warning:
    #: self-report drain, run the grace, exit cleanly — instead of
    #: stopping abruptly (spot/maintenance reclaims deliver SIGTERM)
    drain_on_sigterm: bool = True
    #: >0: poll the accelerator maintenance-event probe this often and
    #: self-drain when an event is imminent (0 disables; the probe is
    #: pluggable via accelerators.tpu.set_metadata_fetcher)
    preemption_probe_period_s: float = 0.0
    #: replicate primary shm object copies to a peer node during drain
    #: so consumers re-fetch instead of paying lineage reconstruction
    drain_flush_objects: bool = True

    # --- serve routing (serve/router.py, serve/replica.py) ---
    #: how often a replica hosting a gossip-capable callable (one that
    #: exposes ``routing_stats()``, e.g. an LLM engine) pushes its load +
    #: prefix digest to the serve controller (propagated to routers via
    #: the long-poll channel). <= 0 disables the reporter thread.
    serve_replica_stats_period_s: float = 0.25
    #: routing stats older than this fall back to pow-2 choice — a
    #: stale digest must not keep steering traffic at a replica whose
    #: cache (or queue) has moved on
    serve_routing_stats_ttl_s: float = 5.0
    #: cache-affinity blend weight: a replica's score is
    #: outstanding_tokens - weight * matched_prefix_tokens, lowest wins.
    #: 1.0 values a cached token exactly as much as a token of queue
    #: backlog (it removes one prefill token of work); raise it to pin
    #: conversations harder, 0 disables affinity (pure least-tokens).
    serve_affinity_weight: float = 1.0
    #: how often the serve controller polls replica.health() (the user
    #: callable's check_health — e.g. the LLM engine's wedged-step-loop
    #: detector) and restarts replicas that ANSWER but report unhealthy.
    #: Liveness reaping alone never catches a stalled engine whose actor
    #: loop still replies. <= 0 disables the poll.
    serve_replica_health_period_s: float = 1.0

    # --- disaggregated prefill/decode serving (inference/kv_transfer.py) ---
    #: budget for the whole prefill-pool handoff (dispatch prefill_export
    #: + KV publish) before the router degrades the request to plain
    #: single-replica generation — the failure ladder's first rung
    serve_disagg_handoff_timeout_s: float = 30.0
    #: prompts whose FULL blocks span fewer tokens than this skip the
    #: disagg handoff entirely (migrating a couple of blocks costs more
    #: than re-prefilling them); also the router's guard when gossip
    #: hasn't told it the engine block size yet
    serve_disagg_min_prompt_tokens: int = 16
    #: published KV exports nobody consumed are reaped after this long
    kv_export_ttl_s: float = 120.0
    #: descriptor-inline payload cap for daemon-less processes (local
    #: mode / unit tests) — bigger exports fail → plain generation
    kv_inline_max_bytes: int = 32 * 1024**2

    # --- cluster-wide KV prefix tier (inference/kv_transfer.py + node_daemon) ---
    #: cap on tier-resident prefix digests a replica advertises through
    #: the routing-stats gossip (MRU subset; the daemon registry can
    #: hold more — adverts are the routable window, not the inventory)
    kv_tier_max_adverts: int = 32
    #: daemon-side tier registry TTL: blocks nobody faulted in for this
    #: long are dropped (and their shm objects deleted). The tier is a
    #: cache, not a durable store.
    kv_tier_ttl_s: float = 600.0
    #: entry cap per daemon tier registry; oldest-first eviction with
    #: object deletion. Bounds shm spent on spilled KV.
    kv_tier_max_entries: int = 512
    #: how long a router keeps tier directory entries sourced from a
    #: DEAD replica before expiring them (the daemon still holds the
    #: bytes — a replacement replica re-adverts within one gossip beat,
    #: so this is the warm-restart bridge window). Explicit retraction
    #: by a LIVE holder purges immediately, not on this TTL.
    kv_tier_advert_ttl_s: float = 30.0
    #: explicit tier namespace override. The daemon tier registry is
    #: node-global and the chain digest names only the TOKENS, so tier
    #: keys are scoped by a model-identity namespace (config + weight
    #: fingerprint, derived per engine) — two deployments of the same
    #: architecture with different weights can never serve each other's
    #: KV. Set this to force a shared (or extra-isolated) namespace.
    kv_tier_namespace: str = ""

    # --- serve ingress (serve/ingress.py: the HTTP/SSE front door) ---
    #: per-request deadline when the client sends none (header
    #: x-request-timeout-s / body timeout_s override, clamped to this as
    #: a ceiling) — stamped into the ambient core/deadline budget so the
    #: engine stops decoding for callers that gave up
    serve_ingress_default_timeout_s: float = 60.0
    #: Retry-After hint (seconds) on pressure sheds; rate-limit sheds
    #: compute the exact bucket-refill wait instead
    serve_ingress_retry_after_s: float = 1.0
    #: default per-tenant token-bucket refill rate, in COST units/s
    #: (cost of one request = prompt tokens + max_new_tokens); tenants
    #: without an explicit TenantPolicy get this
    serve_ingress_default_rate: float = 4000.0
    #: default per-tenant bucket capacity (burst allowance), cost units
    serve_ingress_default_burst: float = 8000.0
    #: how often an ingress replica snapshots per-tenant bucket fill
    #: levels to the serve controller (restored by replacement replicas,
    #: so a restart doesn't refill every tenant's budget). <= 0 disables.
    serve_ingress_bucket_snapshot_period_s: float = 1.0

    # --- runtime_env ---
    #: TTL on the driver-side working_dir/py_modules change-signature
    #: cache: within this window a .remote() carrying a runtime_env
    #: reuses the cached tree signature instead of stat-walking the
    #: whole directory per submit. An edit re-ships at most this many
    #: seconds late. 0 disables the cache (walk every submit).
    tree_signature_ttl_s: float = 5.0

    # --- RPC ---
    #: frames per coalesced batch frame on a connection flush (RPC
    #: micro-batching): a flush packs up to this many queued frames into
    #: one wire frame, so the receiver dispatches them from a single
    #: read wakeup. 1 disables batching (every frame travels alone).
    rpc_batch_max_frames: int = 64
    #: byte ceiling for one batch frame — oversized frames travel alone
    #: so a huge payload can't add head-of-line latency to tiny ones
    rpc_batch_max_bytes: int = 256 * 1024
    #: asyncio StreamReader buffer limit per connection. The stock 64 KiB
    #: limit pauses/resumes the transport every 128 KiB — measured ~0.27
    #: GB/s loopback on the bench box vs ~0.85 GB/s at 2 MiB. Bulk RAW
    #: payloads (chunk transfer) ride the same connections, so this is a
    #: first-order data-plane throughput knob.
    rpc_stream_buffer_bytes: int = 2 * 1024**2
    #: kernel socket send/receive buffer request per RPC connection
    #: (best-effort; uses SO_SNDBUFFORCE/SO_RCVBUFFORCE when privileged
    #: so the wmem_max cap doesn't clamp it). Big socket buffers let the
    #: transport hand a whole chunk to the kernel in one send instead of
    #: memcpy'ing the unsent tail into the asyncio write buffer. 0
    #: leaves the system defaults.
    rpc_socket_buffer_bytes: int = 4 * 1024**2
    rpc_connect_timeout_s: float = 10.0
    rpc_retry_base_delay_s: float = 0.05
    rpc_retry_max_delay_s: float = 2.0
    rpc_max_retries: int = 5
    # --- exactly-once request dedup (core/rpc.py) ---
    #: stamp mutating RPCs with (client id, request id) and answer
    #: retried duplicates from a server-side reply cache instead of
    #: re-executing the handler (the lost-reply trap). Idempotent
    #: methods (rpc.IDEMPOTENT_METHODS) skip the cache entirely.
    rpc_dedup_enabled: bool = True
    #: reply-cache bounds per server process; oldest-first eviction. A
    #: retry arriving after its entry was evicted re-executes — size the
    #: window well past (retry budget × max backoff) worth of traffic.
    rpc_dedup_cache_entries: int = 4096
    rpc_dedup_cache_max_bytes: int = 32 * 1024**2

    # --- task events / observability ---
    task_events_buffer_size: int = 10000
    task_events_flush_period_s: float = 1.0
    metrics_report_period_s: float = 2.0

    # --- testing / chaos ---
    testing_rpc_failure: str = ""  # legacy "method:failure_prob" (pre-handler)
    #: seeded per-method fault plan: "method:mode:prob[:param],..." with
    #: mode in {request_drop, reply_drop, delay, disconnect} — see
    #: util/chaos.py::RpcFaultPlan for the grammar and determinism
    #: contract. Empty = no injection.
    testing_rpc_chaos: str = ""
    #: RNG seed for the fault plan; 0 = generate one (printed at
    #: activation so any failure reproduces from the log)
    testing_rpc_chaos_seed: int = 0
    #: seeded DATA-PLANE fault plan consulted by the pull manager once
    #: per chunk attempt: "mode:prob[:param],..." with mode in
    #: {chunk_drop, chunk_corrupt, chunk_stall, source_die_mid_transfer}
    #: — see util/chaos.py::DataFaultPlan (same determinism contract as
    #: RpcFaultPlan). Empty = no injection.
    testing_pull_chaos: str = ""
    #: RNG seed for the pull fault plan; 0 = generate one (logged at
    #: activation for replay)
    testing_pull_chaos_seed: int = 0
    #: seeded REPLICA fault plan consulted by the LLM engine's step loop
    #: once per executed step phase: "mode:prob[:param][:max],..." with
    #: mode in {kill_mid_decode, kill_mid_prefill, stall} — see
    #: util/chaos.py::ReplicaFaultPlan (same determinism contract as
    #: RpcFaultPlan). Empty = no injection.
    testing_replica_chaos: str = ""
    #: RNG seed for the replica fault plan; 0 = generate one (logged at
    #: activation for replay)
    testing_replica_chaos_seed: int = 0
    #: seeded KV-TIER fault plan consulted by the tier fault-in path
    #: once per phase execution: "mode:prob[:param][:max],..." with mode
    #: in {missing_block, corrupt_block, stale_advert,
    #: kill_mid_migration} — see util/chaos.py::KvTierFaultPlan (same
    #: determinism contract as ReplicaFaultPlan). Empty = no injection.
    testing_kv_tier_chaos: str = ""
    #: RNG seed for the KV-tier fault plan; 0 = generate one (logged at
    #: activation for replay)
    testing_kv_tier_chaos_seed: int = 0
    #: seeded CONTROLLER fault plan consulted by the control plane's
    #: WAL-append ("mutation"), snapshot ("snapshot") and lease-heartbeat
    #: ("lease") paths: "mode:prob[:param][:max],..." with mode in
    #: {kill_mid_mutation, kill_mid_snapshot, partition,
    #: zombie_resurrect} — see util/chaos.py::ControllerFaultPlan (same
    #: determinism contract as ReplicaFaultPlan). Empty = no injection.
    testing_controller_chaos: str = ""
    #: RNG seed for the controller fault plan; 0 = generate one (logged
    #: at activation for replay)
    testing_controller_chaos_seed: int = 0
    #: MASTER chaos seed: when non-zero, every fault plan whose own seed
    #: knob is 0 derives its seed deterministically from this one value
    #: (util/chaos.py::derive_plan_seed — keyed blake2b of the plan
    #: label), so a run arming all three plans reproduces from ONE
    #: logged number instead of three. Explicit per-plan seeds still win.
    testing_chaos_seed: int = 0

    def reset(self) -> None:
        for f in fields(self):
            setattr(self, f.name, f.default)
        self.apply_env()

    def apply_env(self) -> None:
        for f in fields(self):
            env = os.environ.get(_ENV_PREFIX + f.name)
            if env is None:
                continue
            setattr(self, f.name, _parse(env, f.type))

    def apply_system_config(self, overrides: Dict[str, Any]) -> None:
        valid = {f.name: f for f in fields(self)}
        for key, value in overrides.items():
            if key not in valid:
                raise ValueError(f"unknown system_config key: {key!r}")
            setattr(self, key, value)

    def to_dict(self) -> Dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


_STASH_PREFIX = "RAY_TPU_STASHED_"


def scrub_child_env(env: Dict[str, str]) -> Dict[str, str]:
    """Remove ``strip_child_env`` vars from a child-process env, STASHING
    their values under ``RAY_TPU_STASHED_<key>`` so a descendant that
    legitimately needs them (a TPU-assigned worker) can restore them via
    :func:`restore_scrubbed_env`. Mutates and returns ``env``."""
    for key in GLOBAL_CONFIG.strip_child_env.split(","):
        if key and key in env:
            env[_STASH_PREFIX + key] = env.pop(key)
    return env


def restore_scrubbed_env(env: Dict[str, str]) -> Dict[str, str]:
    """Undo :func:`scrub_child_env` for a child that needs the stripped
    vars (TPU-assigned workers). Mutates and returns ``env``."""
    for key in list(env):
        if key.startswith(_STASH_PREFIX):
            env[key[len(_STASH_PREFIX):]] = env.pop(key)
    return env


def _parse(raw: str, typ: Any) -> Any:
    typ = str(typ)
    if "bool" in typ:
        return raw.lower() in ("1", "true", "yes", "on")
    if "int" in typ:
        return int(raw)
    if "float" in typ:
        return float(raw)
    return raw


GLOBAL_CONFIG = GlobalConfig()
GLOBAL_CONFIG.apply_env()


def serialize_config() -> str:
    return json.dumps(GLOBAL_CONFIG.to_dict())


def load_config(serialized: str) -> None:
    GLOBAL_CONFIG.apply_system_config(json.loads(serialized))
