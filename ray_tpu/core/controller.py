"""The cluster control plane (GCS equivalent).

Reference: ``src/ray/gcs/gcs_server/`` — a single authority process holding
node membership + health (``GcsNodeManager``, ``GcsHealthCheckManager``),
the actor FSM with restarts (``GcsActorManager``, ``gcs_actor_manager.h:308``,
restart at ``:548``), GCS-side actor scheduling
(``GcsActorScheduler::ScheduleByGcs``), placement groups with 2PC bundle
reservation (``GcsPlacementGroupManager``), namespaced KV
(``GcsKvManager``), and pubsub fan-out of state changes.

This implementation is the asyncio redesign: one event loop, plain dict
tables (Redis-style persistence is a pluggable later step), RPC service
methods named ``c_*``, push-based subscriptions for actor/node state.
Resource views arrive by periodic daemon sync (ray_syncer pattern,
``ray_syncer/ray_syncer.h:88``) and the same sync reply carries the cluster
view back to daemons for spillback scheduling.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import signal
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core import rpc as _rpc
from ray_tpu.core import wal as _walmod
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu.core.refs import Address
from ray_tpu.core.rpc import (
    RpcClient,
    RpcServer,
    ServerConnection,
    StaleControllerError,
)
from ray_tpu.core.scheduling_policies import (
    BundleReservation,
    pick_node_hybrid,
    place_bundles,
)
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.util.chaos import ControllerFaultPlan, SeededPlanCache

logger = logging.getLogger(__name__)

#: process-wide seeded controller fault plan (util/chaos.py grammar;
#: armed via RAY_TPU_testing_controller_chaos, seed logged at activation)
_PLAN_CACHE = SeededPlanCache(
    ControllerFaultPlan,
    "controller",
    "testing_controller_chaos",
    "testing_controller_chaos_seed",
    logger,
)


def active_controller_fault_plan() -> Optional[ControllerFaultPlan]:
    return _PLAN_CACHE.active()


#: sentinel for "this WAL record has no journaled reply"
_NO_REPLY = object()

ACTOR_PUSH_CHANNEL = 1
NODE_PUSH_CHANNEL = 2
PG_PUSH_CHANNEL = 3
LOG_PUSH_CHANNEL = 4


@dataclass
class NodeInfo:
    node_id: bytes
    host: str
    port: int
    total: Dict[str, float]
    available: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    #: ALIVE | DRAINING | DEAD — DRAINING nodes are excluded from
    #: scheduling/placement but still serve running work and objects
    state: str = "ALIVE"
    drain_reason: str = ""
    last_sync: float = field(default_factory=time.monotonic)
    health_failures: int = 0
    #: latest daemon-synced shm store stats + worker/lease counts
    #: (cluster_status's per-node object view; refreshed every sync)
    store_stats: Dict[str, Any] = field(default_factory=dict)
    num_workers: int = 0
    num_leases: int = 0


@dataclass
class ActorInfo:
    spec: TaskSpec
    state: str = "PENDING"  # PENDING|ALIVE|RESTARTING|DEAD
    address: Optional[Address] = None
    node_id: Optional[bytes] = None
    num_restarts: int = 0
    death_reason: str = ""
    pid: int = 0
    #: True only for snapshot-restored actors awaiting daemon adoption
    restored: bool = False


@dataclass
class PgInfo:
    pg_id: bytes
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "PENDING"  # PENDING|CREATED|REMOVED
    reservations: List[BundleReservation] = field(default_factory=list)
    name: str = ""


class Controller:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None,
                 takeover: bool = False):
        #: optional snapshot file: tables survive a controller restart
        #: (reference: GCS rebuilds from Redis, ``gcs_init_data.cc``)
        self.persist_path = persist_path
        #: True when this incarnation is a promoted hot standby
        #: (controller_main --standby) — surfaced in cluster_status
        self.takeover = takeover
        # Durability/failover sidecar files share the snapshot's
        # directory (the session dir): the write-ahead log, the lease
        # heartbeat file a standby watches, and the durable incarnation
        # epoch. All gated on persist_path — an ephemeral (test-local)
        # controller has no durability contract.
        base = os.path.dirname(os.path.abspath(persist_path)) if persist_path else None
        self._wal_path = os.path.join(base, "controller.wal") if base else None
        self._lease_path = os.path.join(base, "controller.lease") if base else None
        self._epoch_path = os.path.join(base, "controller.epoch") if base else None
        #: incarnation epoch (fencing token): bumped durably on EVERY
        #: start, so a restart/takeover always outranks its predecessor
        self.epoch = 0
        self._wal: Optional[_walmod.WalWriter] = None
        self._lease_task: Optional[asyncio.Task] = None
        #: wall-clock stamp of the last successfully written lease
        #: heartbeat; mutations self-fence when it goes stale (see
        #: _check_fenced — the lease safety margin)
        self._last_lease_ok = time.time()
        #: deposed: a higher epoch exists. Mutations are refused and
        #: stop() must NOT touch the WAL/snapshot (they belong to the
        #: new incumbent now).
        self._fenced = False
        #: chaos (partition/zombie_resurrect): heartbeats suppressed
        #: until this wall-clock stamp, then _silent_mode's resume logic
        self._silent_until = 0.0
        self._silent_mode: Optional[str] = None
        #: daemon addresses learned from registrations AND replayed from
        #: the WAL: a takeover announces its new epoch to these before
        #: it can even bind the old port (fences any zombie writes)
        self._known_daemons: Dict[bytes, Tuple[str, int]] = {}
        #: structured recovery report (snapshot + WAL replay summary),
        #: exposed via cluster_status()["controller"]
        self.recovery_report: Dict[str, Any] = {}
        #: optional hook invoked once when this controller is deposed
        #: (controller_main sets it to trip the process stop event)
        self.on_deposed = None
        self.server = RpcServer(host, port)
        self.nodes: Dict[bytes, NodeInfo] = {}
        self.node_clients: Dict[bytes, RpcClient] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.pgs: Dict[bytes, PgInfo] = {}
        self.named_pgs: Dict[str, bytes] = {}
        # Bounded tombstones for removed PGs: the table drops entries on
        # removal (memory), but clients racing the removal need to tell
        # "removed" apart from "never existed" to fail fast.
        self.removed_pgs: "OrderedDict[bytes, None]" = OrderedDict()
        self.kv: Dict[bytes, bytes] = {}
        self.jobs: Dict[bytes, Dict[str, Any]] = {}
        # Drain object-relocation directory: a draining daemon replicates
        # its primary shm copies to a peer and records the new location
        # here; owners whose cached locations go stale consult this before
        # paying lineage reconstruction. Bounded ring.
        self.relocated_objects: "OrderedDict[bytes, Tuple[bytes, str, int]]" = OrderedDict()
        # task-event ring buffer (``GcsTaskManager`` — serves the state
        # API's `list tasks`; workers push batched lifecycle events)
        self.task_events: "OrderedDict[bytes, Dict[str, Any]]" = OrderedDict()
        # worker-exported timeline event chunks (observability/timeline):
        # BOUNDED by timeline_kv_max_bytes (oldest exports dropped) and
        # reaped per node on death — the fix for the unbounded
        # ``ray_tpu:events:*`` KV growth the old export path had. Keyed
        # by (exporter uid, pid, chunk), value = (node_id, blob).
        self.timeline_exports: "OrderedDict[str, Tuple[bytes, bytes]]" = OrderedDict()
        self._timeline_export_bytes = 0
        self._subscribers: Set[ServerConnection] = set()
        # channel → connections that asked for it (None entry = legacy
        # subscribe-to-everything); high-volume channels (logs) only go
        # where requested
        self._channel_subs: Dict[int, Set[ServerConnection]] = {}
        self._metrics_server = None
        self._health_task: Optional[asyncio.Task] = None
        self._persist_task: Optional[asyncio.Task] = None
        self._mutations = 0  # bumped on persisted-table changes
        self._stopping = False
        for name in [m for m in dir(self) if m.startswith("c_")]:
            self.server.register(name[2:], getattr(self, name))
        self.server.on_disconnect = self._on_disconnect

    async def start(self) -> int:
        restored_port = self._load_snapshot()
        if not restored_port and self._lease_path:
            # no snapshot tick ever ran (crash inside the first period):
            # the lease heartbeat file still records the bound port, so
            # a restart can rebind it and keep every client's address
            lease = _walmod.read_lease(self._lease_path)
            if lease is not None:
                restored_port = lease.get("port") or None
        wal_records = self._open_and_replay_wal()
        self._bump_epoch()
        if self.recovery_report:
            self.recovery_report["wal_records"] = wal_records
            self.recovery_report["epoch"] = self.epoch
            logger.info(
                "controller recovery: restored kv=%d pgs=%d actors=%d "
                "wal_records=%d epoch=%d",
                self.recovery_report.get("kv", 0),
                self.recovery_report.get("pgs", 0),
                self.recovery_report.get("actors", 0),
                wal_records, self.epoch,
            )
        if self._lease_path:
            # claim the lease BEFORE binding: a resumed zombie's next
            # lease read must see the higher epoch and stand down
            self._write_lease()
            # a takeover/restart announces its epoch to every daemon it
            # knows from the WAL — this fences zombie writes even while
            # the old incumbent still holds the port we want
            if self._known_daemons:
                asyncio.ensure_future(self._announce_to_daemons())
        if restored_port and self.server.port == 0:
            # a restarted controller rebinds its old port so daemons'
            # existing retry loops can reconnect without rediscovery
            self.server.port = restored_port
        try:
            port = await self.server.start()
        except OSError:
            # Old port still held — usually the predecessor's socket not
            # yet released after a SIGKILL (or a deposed incumbent that
            # hasn't self-fenced yet). The old port is the ONLY address
            # daemons and drivers know, so spend a short patience window
            # retrying before falling back to a fresh port (which
            # strands every existing client on the dead address).
            port = None
            target = self.server.port
            if target and (restored_port == target or self.takeover):
                for _ in range(50):
                    await asyncio.sleep(0.1)
                    if self._lease_path:
                        self._write_lease()  # keep the claim fresh
                    try:
                        port = await self.server.start()
                        break
                    except OSError:
                        continue
            if port is None:
                self.server.port = 0
                port = await self.server.start()
        self._loop = asyncio.get_event_loop()  # /federate bridges here
        self._health_task = asyncio.ensure_future(self._health_loop())
        if self.persist_path:
            self._persist_task = asyncio.ensure_future(self._persist_loop())
        if self._lease_path:
            self._write_lease()  # now carries the bound port
            self._lease_task = asyncio.ensure_future(self._lease_loop())
        self._start_metrics()
        # hang defense: stall watchdog on the control-plane loop (one
        # blocked handler here wedges the whole cluster's control plane)
        from ray_tpu.observability.event_stats import install_loop_monitor

        install_loop_monitor(asyncio.get_event_loop(), "controller")
        return port

    # ---- persistence (GCS restart recovery) ----------------------------
    def _snapshot(self) -> Dict[str, Any]:
        return {
            "port": getattr(self.server, "port", 0),
            "epoch": self.epoch,
            "daemons": dict(self._known_daemons),
            "relocated": dict(self.relocated_objects),
            "kv": dict(self.kv),
            "jobs": dict(self.jobs),
            "named_actors": dict(self.named_actors),
            "named_pgs": dict(self.named_pgs),
            "pgs": {
                pg_id: {
                    "bundles": info.bundles,
                    "strategy": info.strategy,
                    "name": info.name,
                }
                for pg_id, info in self.pgs.items()
            },
            "actors": {
                actor_id: {
                    "spec": info.spec,
                    "num_restarts": info.num_restarts,
                }
                for actor_id, info in self.actors.items()
                if info.state != "DEAD"
            },
        }

    def _mark_dirty(self) -> None:
        self._mutations += 1

    def _write_snapshot(self) -> None:
        """Durable atomic snapshot write shared by the loop and clean
        shutdown: tmp + fsync(file) + rename + fsync(dir) — a crash
        mid-write must never clobber the last good snapshot, and a HOST
        crash must never surface a zero-length or stale one (the
        historical tmp+rename alone did not fsync either the bytes or
        the directory entry). A committed snapshot is a WAL compaction
        point: everything it captures is redundant with the log, so the
        log truncates atomically right after. Both steps run
        synchronously on the event loop — no mutation can interleave
        between the state capture and the truncate."""
        plan = active_controller_fault_plan()
        fault = plan.consult("snapshot") if plan is not None else None
        tmp = self.persist_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._snapshot(), f)
            f.flush()
            os.fsync(f.fileno())
        if fault is not None and fault[0] == "kill_mid_snapshot":
            # die between the durable tmp write and the rename-commit:
            # recovery must use the LAST GOOD snapshot + the full WAL
            logger.warning("chaos: kill_mid_snapshot — SIGKILLing controller")
            os.kill(os.getpid(), signal.SIGKILL)
        _walmod.durable_replace(tmp, self.persist_path)
        if self._wal is not None:
            from ray_tpu.observability.rpc_metrics import (
                CONTROLLER_WAL_TRUNCATIONS,
            )

            self._wal.truncate()
            CONTROLLER_WAL_TRUNCATIONS.inc()

    async def _persist_loop(self) -> None:
        persisted = -1
        while not self._stopping:
            await asyncio.sleep(GLOBAL_CONFIG.controller_persist_interval_s)
            if self._mutations == persisted:
                continue  # nothing changed: skip the pickle+write churn
            if self._lease_stale():
                # deposed, or silent past the ack fence: a standby may
                # own the session files now — writing OUR snapshot (and
                # truncating the WAL the takeover replays from) would
                # clobber the successor's state
                continue
            try:
                persisted = self._mutations
                self._write_snapshot()
            except Exception:
                logger.exception("controller snapshot failed")

    # ---- write-ahead log / incarnation epoch / lease (core/wal.py) -----
    def _wal_append(self, op: str, data: Dict[str, Any], reply=_NO_REPLY) -> None:
        """Journal one table mutation BEFORE its RPC reply is sent (the
        handler returns → dispatch replies → so an append inside the
        handler always precedes the ack). ``reply`` is journaled with
        the caller's dedup key so recovery re-seeds the exactly-once
        reply cache. Also the self-fencing choke point: a controller
        whose lease went stale must stop acking — a standby may already
        own the tables."""
        self._check_fenced()
        self._mark_dirty()
        if self._wal is None:
            return
        rec: Dict[str, Any] = {"op": op, "d": data}
        if reply is not _NO_REPLY:
            key = _rpc.current_dedup_key()
            if key is not None:
                rec["k"] = [key[0], key[1]]
                rec["r"] = pickle.dumps(reply, protocol=5)
        nbytes = self._wal.append(rec)
        from ray_tpu.observability.rpc_metrics import (
            CONTROLLER_WAL_APPENDS,
            CONTROLLER_WAL_BYTES,
        )

        CONTROLLER_WAL_APPENDS.inc()
        CONTROLLER_WAL_BYTES.inc(nbytes)
        plan = active_controller_fault_plan()
        fault = plan.consult("mutation") if plan is not None else None
        if fault is not None and fault[0] == "kill_mid_mutation":
            # die with the mutation logged but the reply unsent: replay
            # must surface it and the client's retry must hit the
            # re-seeded dedup cache, not a second execution
            logger.warning("chaos: kill_mid_mutation — SIGKILLing controller")
            os.kill(os.getpid(), signal.SIGKILL)

    def _open_and_replay_wal(self) -> int:
        """Open the session WAL and replay every record appended since
        the last snapshot compaction: recovery becomes byte-exact up to
        the last acked mutation instead of the last snapshot tick."""
        if not self._wal_path:
            return 0
        replayed = 0
        try:
            for rec in _walmod.replay(self._wal_path):
                try:
                    self._apply_wal_record(rec)
                    replayed += 1
                except Exception:
                    logger.exception("WAL record apply failed: %r", rec.get("op"))
        except Exception:
            logger.exception("controller WAL replay failed")
        self._wal = _walmod.WalWriter(
            self._wal_path, fsync_every=GLOBAL_CONFIG.controller_wal_fsync
        )
        if replayed:
            from ray_tpu.observability.rpc_metrics import CONTROLLER_WAL_REPLAYS

            CONTROLLER_WAL_REPLAYS.inc(replayed)
            if not self.recovery_report:
                self.recovery_report = {"kv": len(self.kv), "pgs": len(self.pgs),
                                        "actors": len(self.actors), "snapshot": False}
        return replayed

    def _apply_wal_record(self, rec: Dict[str, Any]) -> None:
        """Re-apply one journaled mutation to the tables (inverse of the
        ``_wal_append`` call sites), then re-seed the dedup reply cache
        when the record journaled an acked reply."""
        op, d = rec["op"], rec["d"]
        if op == "kv_put":
            self.kv[d["key"]] = d["value"]
        elif op == "kv_del":
            self.kv.pop(d["key"], None)
        elif op == "actor_register":
            spec: TaskSpec = pickle.loads(d["spec"])
            self.actors[spec.actor_id] = ActorInfo(
                spec=spec, state="RESTARTING", restored=True,
            )
            if spec.actor_name:
                self.named_actors[(spec.namespace or "", spec.actor_name)] = spec.actor_id
        elif op == "actor_restart":
            info = self.actors.get(pickle.loads(d["actor_id"]))
            if info is not None:
                info.num_restarts = d["num_restarts"]
        elif op == "actor_death":
            actor_id = pickle.loads(d["actor_id"])
            info = self.actors.get(actor_id)
            if info is not None:
                info.state = "DEAD"
                info.death_reason = d.get("reason", "")
                info.restored = False
        elif op == "pg_create":
            self.pgs[d["pg_id"]] = PgInfo(
                pg_id=d["pg_id"], bundles=d["bundles"],
                strategy=d["strategy"], name=d.get("name", ""),
                state="RESTORING",
            )
            if d.get("name"):
                self.named_pgs[d["name"]] = d["pg_id"]
        elif op == "pg_remove":
            info = self.pgs.pop(d["pg_id"], None)
            if info is not None and info.name:
                self.named_pgs.pop(info.name, None)
            self.removed_pgs[d["pg_id"]] = None
            while len(self.removed_pgs) > 4096:
                self.removed_pgs.popitem(last=False)
        elif op == "job_register":
            self.jobs[d["job_id"]] = pickle.loads(d["info"])
        elif op == "relocated":
            for m in d["moves"]:
                self.relocated_objects[m["object_id"]] = (
                    m["node_id"], m["host"], m["port"],
                )
            while len(self.relocated_objects) > 65536:
                self.relocated_objects.popitem(last=False)
        elif op == "node_register":
            self._known_daemons[d["node_id"]] = (d["host"], d["port"])
        else:
            logger.warning("unknown WAL op %r (skipped)", op)
        key, reply = rec.get("k"), rec.get("r")
        if key is not None and reply is not None:
            self.server.seed_dedup(
                (bytes(key[0]), key[1]), (_rpc.REPLY_OK, reply)
            )

    def _bump_epoch(self) -> None:
        """Every incarnation takes a strictly higher epoch, durably,
        BEFORE serving: fencing depends on a restart/takeover always
        outranking its predecessor (snapshot epoch covers the case where
        the epoch file is lost; the max of both is authoritative)."""
        if not self._epoch_path:
            self.epoch = 1
            return
        try:
            with open(self._epoch_path, "rb") as f:
                self.epoch = max(self.epoch, int(f.read().decode() or 0))
        except FileNotFoundError:
            pass
        except Exception:
            logger.exception("controller epoch file read failed")
        self.epoch += 1
        _walmod.write_durable(self._epoch_path, str(self.epoch).encode())
        from ray_tpu.observability.rpc_metrics import CONTROLLER_EPOCH

        CONTROLLER_EPOCH.set(self.epoch)

    def _write_lease(self) -> None:
        _walmod.write_lease(
            self._lease_path,
            epoch=self.epoch,
            port=getattr(self.server, "port", 0),
            pid=os.getpid(),
            ts=time.time(),
        )
        self._last_lease_ok = time.time()

    def _lease_stale(self) -> bool:
        """True once this incarnation may no longer own the tables:
        deposed outright, or its own lease heartbeat is stale past ~75%
        of the takeover timeout — a standby assumes the lease dead at
        100%, so distrusting ourselves strictly earlier closes the
        split-brain window (the classic lease safety margin)."""
        if self._fenced:
            return True
        if self._lease_path is None or self._lease_task is None:
            return False
        return (
            time.time() - self._last_lease_ok
            > 0.75 * GLOBAL_CONFIG.controller_lease_timeout_s
        )

    def _check_fenced(self) -> None:
        """Mutation self-fence: refuse to ack once ``_lease_stale``.
        Raises a ConnectionLost subclass so clients transparently retry
        against the new incumbent."""
        if self._fenced:
            raise StaleControllerError(
                f"stale_controller: epoch {self.epoch} was deposed",
                seen_epoch=self.epoch,
            )
        if self._lease_stale():
            raise StaleControllerError(
                f"stale_controller: lease heartbeat stale (epoch {self.epoch}) "
                "— refusing to ack mutations a standby may now own",
                seen_epoch=self.epoch,
            )

    async def _lease_loop(self) -> None:
        """Active-side lease heartbeat (+ the chaos hook for partition /
        zombie_resurrect). Reads before writing: a lease claimed by a
        HIGHER epoch means a standby took over — we are deposed and must
        exit without touching the WAL or snapshot."""
        interval = GLOBAL_CONFIG.controller_lease_interval_s
        while not self._stopping:
            await asyncio.sleep(interval)
            now = time.time()
            if self._silent_until:
                if now < self._silent_until:
                    continue  # chaos partition window: no heartbeats
                mode, self._silent_mode = self._silent_mode, None
                self._silent_until = 0.0
                await self._resume_from_partition(mode)
                continue
            plan = active_controller_fault_plan()
            fault = plan.consult("lease") if plan is not None else None
            if fault is not None and fault[0] in ("partition", "zombie_resurrect"):
                logger.warning(
                    "chaos: %s — suppressing lease heartbeats for %.1fs",
                    fault[0], fault[1],
                )
                self._silent_mode = fault[0]
                self._silent_until = now + fault[1]
                continue
            lease = _walmod.read_lease(self._lease_path)
            if lease is not None and lease.get("epoch", 0) > self.epoch:
                self._depose(f"lease held by epoch {lease['epoch']}")
                return
            try:
                self._write_lease()
            except Exception:
                logger.exception("lease heartbeat write failed")

    async def _resume_from_partition(self, mode: Optional[str]) -> None:
        """The deposed side of a chaos partition window. ``partition``:
        re-read the lease; a higher-epoch claim means stand down.
        ``zombie_resurrect``: FIRST blindly attempt a daemon write with
        our (stale) epoch — the daemons' fencing gate must reject it
        with ``stale_controller`` — then stand down."""
        if mode == "zombie_resurrect":
            fenced = await self._announce_to_daemons()
            if fenced:
                self._depose("zombie write fenced by daemons")
                return
        lease = _walmod.read_lease(self._lease_path)
        if lease is not None and lease.get("epoch", 0) > self.epoch:
            self._depose(f"lease held by epoch {lease['epoch']} after partition")
            return
        # nobody took over (no standby): resume heartbeating
        self._write_lease()

    def _depose(self, reason: str) -> None:
        """A higher incarnation owns the cluster: stop acking, never
        touch the WAL/snapshot again, and tell the host process to exit
        (the standby is waiting to rebind our port)."""
        if self._fenced:
            return
        self._fenced = True
        logger.warning(
            "controller epoch %d deposed (%s): exiting", self.epoch, reason
        )
        cb = self.on_deposed
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("on_deposed callback failed")
        else:
            # standalone/no-host fallback: free the port for the
            # incumbent — a deposed controller serving reads is a lie
            os.kill(os.getpid(), signal.SIGKILL)

    async def _announce_to_daemons(self) -> bool:
        """Push ``controller_hello`` (stamped with our epoch) to every
        daemon learned from the WAL/snapshot. For a new incumbent this
        fences the old epoch cluster-wide before we even bind the port;
        for a resurrected zombie it is the write that MUST bounce.
        Returns True when any daemon fenced us."""
        fenced = False
        for node_id, (host, dport) in list(self._known_daemons.items()):
            client = RpcClient(host, dport, name="noded", role="noded")
            client.fencing_epoch = self.epoch
            try:
                await client.call(
                    "controller_hello",
                    {"epoch": self.epoch, "port": getattr(self.server, "port", 0)},
                    timeout=2.0,
                )
            except StaleControllerError:
                fenced = True
            except Exception:
                pass  # daemon gone/unreachable — registration will sort it
            finally:
                try:
                    await client.close()
                except Exception:
                    pass
        return fenced

    def _load_snapshot(self) -> Optional[int]:
        """Restart recovery: restore KV/jobs/PGs/actors from the snapshot.
        PGs re-run 2PC (daemon prepare/commit are idempotent, so bundles
        still held by live daemons are simply re-adopted); actors come
        back RESTARTING and are adopted ALIVE when their daemon's next
        sync reports them running — see ``c_sync_resources``."""
        if not self.persist_path:
            return None
        if not os.path.exists(self.persist_path):
            return None
        try:
            with open(self.persist_path, "rb") as f:
                snap = pickle.load(f)
        except Exception:
            logger.exception("controller snapshot load failed")
            return None
        self.epoch = max(self.epoch, int(snap.get("epoch", 0)))
        self._known_daemons.update(snap.get("daemons", {}))
        self.relocated_objects.update(snap.get("relocated", {}))
        self.kv.update(snap.get("kv", {}))
        self.jobs.update(snap.get("jobs", {}))
        self.named_actors.update(snap.get("named_actors", {}))
        self.named_pgs.update(snap.get("named_pgs", {}))
        for pg_id, p in snap.get("pgs", {}).items():
            info = PgInfo(
                pg_id=pg_id, bundles=p["bundles"], strategy=p["strategy"],
                name=p["name"], state="RESTORING",
            )
            self.pgs[pg_id] = info
        for actor_id, a in snap.get("actors", {}).items():
            self.actors[actor_id] = ActorInfo(
                spec=a["spec"],
                state="RESTARTING",
                num_restarts=a["num_restarts"],
                restored=True,
            )
        if snap.get("actors") or snap.get("pgs"):
            asyncio.ensure_future(self._reconcile_restored_state())
        # the one-line summary is logged from start() once the WAL
        # replay count and the new epoch are known
        self.recovery_report = {
            "kv": len(snap.get("kv", {})),
            "pgs": len(snap.get("pgs", {})),
            "actors": len(snap.get("actors", {})),
            "snapshot": True,
        }
        return snap.get("port") or None

    async def _reconcile_restored_state(self) -> None:
        """After a grace window for daemons to re-register/sync: restored
        PGs whose bundles weren't fully re-adopted are released and
        rescheduled; restored actors not adopted are re-scheduled FRESH
        (no restart budget consumed — the controller dying is not the
        actor's failure). A daemon partitioned longer than the grace
        window can still yield a duplicate actor; the reference carries
        the same trade-off in its raylet-reconnect window."""
        await asyncio.sleep(GLOBAL_CONFIG.controller_restore_grace_s)
        for pg_id, info in list(self.pgs.items()):
            if info.state != "RESTORING":
                continue
            if len(info.reservations) == len(info.bundles):
                info.state = "CREATED"
                await self._publish(PG_PUSH_CHANNEL, {"pg_id": pg_id, "state": "CREATED"})
                continue
            # partial/no adoption: release what was adopted, reschedule
            for res in info.reservations:
                client = self.node_clients.get(res.node_id)
                if client is not None:
                    try:
                        await client.call(
                            "release_bundle",
                            {"pg_id": pg_id, "bundle_index": res.bundle_index},
                            timeout=10,
                        )
                    except Exception:
                        pass
            info.reservations = []
            info.state = "PENDING"
            asyncio.ensure_future(self._schedule_pg(pg_id))
        for actor_id, info in list(self.actors.items()):
            if info.restored and info.state == "RESTARTING" and info.address is None:
                info.restored = False
                logger.info(
                    "restored actor %s not adopted; rescheduling fresh",
                    actor_id.hex()[:8],
                )
                asyncio.ensure_future(self._schedule_actor(actor_id))

    def _start_metrics(self) -> None:
        if not GLOBAL_CONFIG.metrics_export_enabled:
            return
        from ray_tpu.observability.metrics import Gauge, MetricsServer, on_collect

        g_nodes = Gauge("raytpu_nodes", "cluster nodes", ("state",))
        g_actors = Gauge("raytpu_actors", "actors by state", ("state",))
        g_pgs = Gauge("raytpu_placement_groups", "placement groups by state", ("state",))

        def sample() -> None:
            alive = sum(1 for n in self.nodes.values() if n.alive)
            g_nodes.set(alive, {"state": "alive"})
            g_nodes.set(len(self.nodes) - alive, {"state": "dead"})
            by_state: Dict[str, int] = {}
            for info in self.actors.values():
                by_state[info.state] = by_state.get(info.state, 0) + 1
            for state in ("PENDING", "ALIVE", "RESTARTING", "DEAD"):
                g_actors.set(by_state.get(state, 0), {"state": state})
            pg_states: Dict[str, int] = {}
            for info in self.pgs.values():
                pg_states[info.state] = pg_states.get(info.state, 0) + 1
            for state in ("PENDING", "CREATED"):
                g_pgs.set(pg_states.get(state, 0), {"state": state})

        self._metrics_cb = on_collect(sample)
        # /federate: one scrape returns EVERY node's registry with node
        # labels (the controller fans out to the daemons' metrics_text
        # RPC) — point Prometheus at this instead of per-node targets
        self._metrics_server = MetricsServer(
            host=GLOBAL_CONFIG.metrics_bind_host,
            port=GLOBAL_CONFIG.metrics_port,
            routes={"/federate": self._federate_blocking},
        )
        logger.info(
            "controller metrics at http://127.0.0.1:%d/metrics "
            "(cluster federation at /federate)",
            self._metrics_server.port,
        )

    def _federate_blocking(self) -> str:
        """HTTP-thread bridge for /federate: run the async fan-out on
        the controller loop and wait bounded."""
        loop = getattr(self, "_loop", None)
        if loop is None or not loop.is_running():
            return ""
        fut = asyncio.run_coroutine_threadsafe(self._federated_text(), loop)
        return fut.result(timeout=15)

    async def _federated_text(self) -> str:
        """Every registered node's /metrics registry plus the
        controller's own, each series stamped with a ``node`` label.
        Duplicate HELP/TYPE comment lines are emitted once."""
        from ray_tpu.observability.metrics import inject_label, render

        loop = asyncio.get_event_loop()
        own = await loop.run_in_executor(None, render)
        parts = [inject_label(own, "node", "controller")]
        items = list(self.node_clients.items())

        async def one(node_id: bytes, client: RpcClient) -> str:
            try:
                text = await client.call("metrics_text", {}, timeout=10)
                return inject_label(text, "node", node_id.hex()[:12])
            except Exception:
                return ""  # dead/slow node: omit from this scrape

        parts += [
            t
            for t in await asyncio.gather(*[one(n, c) for n, c in items])
            if t
        ]
        seen_comments: set = set()
        out: List[str] = []
        for text in parts:
            for line in text.splitlines():
                if line.startswith("#"):
                    key = " ".join(line.split()[:3])  # "# TYPE <name>"
                    if key in seen_comments:
                        continue
                    seen_comments.add(key)
                out.append(line)
        return "\n".join(out) + "\n"

    @property
    def metrics_port(self) -> int:
        return self._metrics_server.port if self._metrics_server else 0

    async def stop(self) -> None:
        self._stopping = True
        from ray_tpu.observability.event_stats import remove_loop_monitor

        remove_loop_monitor(asyncio.get_event_loop())
        if self._lease_task is not None:
            self._lease_task.cancel()
        if self._persist_task is not None:
            self._persist_task.cancel()
            # final consistent snapshot on clean shutdown (atomic write:
            # a kill mid-dump must not truncate the last good snapshot).
            # A DEPOSED (or lease-stale) controller skips this entirely:
            # the snapshot and WAL belong to the new incumbent now — and
            # the WAL still holds everything we acked, so skipping loses
            # nothing even on a false-positive staleness read.
            if not self._lease_stale():
                try:
                    self._write_snapshot()
                except Exception:
                    pass
        if self._wal is not None:
            self._wal.close()
        if self._lease_path and not self._lease_stale():
            # clean shutdown releases the lease (ts=0): a waiting
            # standby promotes immediately instead of riding out the
            # full staleness timeout
            try:
                _walmod.write_lease(
                    self._lease_path, epoch=self.epoch,
                    port=getattr(self.server, "port", 0),
                    pid=os.getpid(), ts=0.0,
                )
            except Exception:
                pass
        if self._metrics_server is not None:
            from ray_tpu.observability.metrics import remove_collect

            remove_collect(self._metrics_cb)
            self._metrics_server.stop()
        if self._health_task:
            self._health_task.cancel()
        for c in self.node_clients.values():
            await c.close()
        await self.server.stop()

    def _on_disconnect(self, conn: ServerConnection) -> None:
        self._subscribers.discard(conn)
        for subs in self._channel_subs.values():
            subs.discard(conn)

    # ---- pubsub --------------------------------------------------------
    async def _publish(self, channel: int, payload: Any) -> None:
        # state pushes carry the incarnation epoch: subscribers drop
        # pushes from a deposed controller that hasn't noticed yet
        # (core_worker-side half of epoch fencing)
        if isinstance(payload, dict) and self.epoch:
            payload = {**payload, "controller_epoch": self.epoch}
        # legacy all-channel subscribers ∪ explicit channel subscribers
        conns = list(self._subscribers | self._channel_subs.get(channel, set()))

        async def push_one(c: ServerConnection):
            try:
                await c.push(channel, payload)
                return None
            except Exception:
                return c

        # concurrent: one slow connection must not stall every other
        # subscriber's push (nor the caller)
        dead = [c for c in await asyncio.gather(*[push_one(c) for c in conns]) if c]
        for conn in dead:
            self._subscribers.discard(conn)
            for subs in self._channel_subs.values():
                subs.discard(conn)

    async def c_subscribe(self, payload, conn: ServerConnection):
        """Subscribe this connection to pushes. ``channels``: explicit
        channel list; omitted = all broadcast channels (legacy)."""
        channels = (payload or {}).get("channels")
        if channels is None:
            self._subscribers.add(conn)
        else:
            for ch in channels:
                self._channel_subs.setdefault(ch, set()).add(conn)
        return True

    # ---- nodes & resource sync ----------------------------------------
    async def c_register_node(self, payload, conn):
        info = NodeInfo(
            node_id=payload["node_id"],
            host=payload["host"],
            port=payload["port"],
            total=payload["resources"],
            available=dict(payload["resources"]),
            labels=payload.get("labels", {}),
        )
        self.nodes[info.node_id] = info
        stale = self.node_clients.pop(info.node_id, None)
        if stale is not None:
            # re-registration (e.g. a dedup-window miss replaying after a
            # chaos'd reply): don't leak the old client's read task
            asyncio.ensure_future(stale.close())
        client = RpcClient(info.host, info.port, name="noded", role="noded")
        # controller-originated daemon writes carry the incarnation
        # epoch: the daemon's fencing gate rejects a deposed controller
        client.fencing_epoch = self.epoch
        self.node_clients[info.node_id] = client
        # journal the daemon's address: a takeover (or resurrected
        # zombie) must be able to reach daemons BEFORE any of them
        # re-registers — see _announce_to_daemons
        self._known_daemons[info.node_id] = (info.host, info.port)
        self._wal_append(
            "node_register",
            {"node_id": info.node_id, "host": info.host, "port": info.port},
            reply={"ok": True},
        )
        # Re-adoption: a (re)registering daemon reports the PG bundles it
        # still holds; a restarted controller reattaches them to RESTORING
        # PGs instead of double-reserving elsewhere.
        for b in payload.get("bundles", []):
            pg = self.pgs.get(b["pg_id"])
            if pg is not None and pg.state == "RESTORING":
                pg.reservations.append(
                    BundleReservation(
                        node_id=info.node_id,
                        bundle_index=b["bundle_index"],
                        resources=b["resources"],
                    )
                )
        logger.info("node %s registered (%s)", info.node_id.hex()[:8], info.total)
        await self._publish(NODE_PUSH_CHANNEL, {"node_id": info.node_id, "alive": True})
        return {"ok": True}

    async def c_sync_resources(self, payload, conn):
        """Daemon heartbeat: report availability, receive the cluster view
        (the ray_syncer exchange)."""
        node = self.nodes.get(payload["node_id"])
        if node is None:
            # restarted controller: this daemon predates us — ask it to
            # re-register (carrying its held bundles for re-adoption)
            return {"unknown_node": True, "view": [],
                    "controller_epoch": self.epoch}
        node.available = payload["available"]
        node.total = payload.get("total", node.total)
        node.pending_leases = payload.get("pending_leases", [])
        node.store_stats = payload.get("store", node.store_stats)
        node.num_workers = payload.get("num_workers", node.num_workers)
        node.num_leases = payload.get("num_leases", node.num_leases)
        node.last_sync = time.monotonic()
        node.health_failures = 0
        # adopt running actors a restored controller only knows as
        # RESTARTING-from-snapshot (restart recovery reconciliation)
        for a in payload.get("actors", []):
            info = self.actors.get(a["actor_id"])
            if (
                info is not None
                and info.restored
                and info.state == "RESTARTING"
                and info.address is None
            ):
                info.restored = False
                info.state = "ALIVE"
                info.address = Address(
                    worker_id=b"", node_id=payload["node_id"],
                    host=a["host"], port=a["port"],
                )
                info.node_id = payload["node_id"]
                info.pid = a["pid"]
                await self._publish(
                    ACTOR_PUSH_CHANNEL,
                    {"actor_id": a["actor_id"], "state": "ALIVE", "address": info.address},
                )
        return {
            # every sync reply carries the incarnation epoch, so daemons
            # passively learn the current fencing floor without any
            # controller-initiated write having happened yet
            "controller_epoch": self.epoch,
            # DRAINING nodes are omitted: daemons use this view for
            # spillback targets and data block placement — neither may
            # land new work on a node about to disappear
            "view": [
                {
                    "node_id": n.node_id,
                    "host": n.host,
                    "port": n.port,
                    "total": n.total,
                    "available": n.available,
                    "alive": n.alive,
                    "labels": n.labels,
                }
                for n in self.nodes.values()
                if n.alive and n.state != "DRAINING"
            ]
        }

    async def c_nodes(self, payload, conn):
        return [
            {
                "NodeID": n.node_id.hex(),
                "node_id": n.node_id,
                "Alive": n.alive,
                "State": n.state,
                "DrainReason": n.drain_reason,
                "Resources": n.total,
                "Available": n.available,
                "host": n.host,
                "port": n.port,
                "Labels": n.labels,
            }
            for n in self.nodes.values()
        ]

    async def c_autoscaler_demand(self, payload, conn):
        """Demand snapshot for the autoscaler (reference
        ``gcs_autoscaler_state_manager.h`` load report): resource shapes
        the cluster cannot currently place, plus per-node utilization."""
        pending_tasks: List[Dict[str, float]] = []
        for n in self.nodes.values():
            if n.alive:
                pending_tasks.extend(getattr(n, "pending_leases", []))
        pending_actors = [
            dict(info.spec.resources)
            for info in self.actors.values()
            if info.state == "PENDING"
        ]
        pending_bundles: List[Dict[str, float]] = []
        for pg in self.pgs.values():
            if pg.state == "PENDING":
                pending_bundles.extend(dict(b) for b in pg.bundles)
        return {
            "pending_tasks": pending_tasks,
            "pending_actors": pending_actors,
            "pending_bundles": pending_bundles,
            "nodes": [
                {
                    "node_id": n.node_id.hex(),
                    "alive": n.alive,
                    "state": n.state,
                    "total": n.total,
                    "available": n.available,
                    "labels": n.labels,
                }
                for n in self.nodes.values()
            ],
        }

    async def c_cluster_resources(self, payload, conn):
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.total.items():
                out[k] = out.get(k, 0) + v
        return out

    async def c_available_resources(self, payload, conn):
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.available.items():
                out[k] = out.get(k, 0) + v
        return out

    async def _health_loop(self) -> None:
        """Daemon health via resource-sync staleness (the syncer heartbeats
        every ~200ms) plus an active ping with a short connect timeout
        (``gcs_health_check_manager.h:39``)."""
        period = GLOBAL_CONFIG.health_check_period_s
        threshold = GLOBAL_CONFIG.health_check_failure_threshold
        while not self._stopping:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if not node.alive:
                    continue
                if now - node.last_sync <= period * threshold:
                    node.health_failures = 0
                    continue
                client = self.node_clients.get(node.node_id)
                try:
                    await client.call("ping", timeout=period, connect_timeout=period)
                    node.health_failures = 0
                except Exception:
                    # stale sync + N consecutive failed pings → dead
                    node.health_failures += 1
                    if node.health_failures >= threshold:
                        await self._mark_node_dead(node, "health check failed")

    async def _mark_node_dead(self, node: NodeInfo, reason: str) -> None:
        if not node.alive:
            return
        drained = node.state == "DRAINING"
        node.alive = False
        node.state = "DEAD"
        # a dead daemon is no longer an announce target (in-memory only:
        # a re-registration re-journals it)
        self._known_daemons.pop(node.node_id, None)
        logger.warning("node %s dead: %s", node.node_id.hex()[:8], reason)
        await self._publish(
            NODE_PUSH_CHANNEL,
            {"node_id": node.node_id, "alive": False, "state": "DEAD"},
        )
        # Reap the dead node's timeline exports: its workers can never
        # export again, and the ring must not carry their chunks forever
        # (the worker-deregistration half of bounded retention).
        stale_keys = [
            k
            for k, (nid, _b) in self.timeline_exports.items()
            if nid == node.node_id
        ]
        for k in stale_keys:
            _nid, blob = self.timeline_exports.pop(k)
            self._timeline_export_bytes -= len(blob)
        # Fail over actors that lived there. A drained node's deaths are
        # not the actors' fault: their restarts consume no budget.
        for actor_id, info in list(self.actors.items()):
            if info.node_id == node.node_id and info.state in ("ALIVE", "PENDING", "RESTARTING"):
                await self._handle_actor_death(
                    actor_id, f"node died: {reason}", drained=drained
                )

    async def c_drain_node(self, payload, conn):
        """Enter the drain protocol (reference ``DrainNode`` in GCS): the
        node leaves the scheduling pool but keeps serving running work and
        objects until its daemon deregisters (or dies). Called by the
        daemon itself on a preemption warning, or by operators/tests."""
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {"ok": False}
        if node.alive and node.state != "DRAINING":
            node.state = "DRAINING"
            node.drain_reason = payload.get("reason", "drain requested")
            logger.warning(
                "node %s draining: %s", node.node_id.hex()[:8], node.drain_reason
            )
            await self._publish(
                NODE_PUSH_CHANNEL,
                {
                    "node_id": node.node_id,
                    "alive": True,
                    "state": "DRAINING",
                    "reason": node.drain_reason,
                },
            )
            # operator/test-initiated drains must reach the daemon too
            # (the daemon's own self-report path makes this a no-op there)
            client = self.node_clients.get(node.node_id)
            if client is not None:
                async def _forward():
                    try:
                        await client.call(
                            "drain", {"reason": node.drain_reason}, timeout=10
                        )
                    except Exception:
                        pass  # daemon already draining or gone

                asyncio.ensure_future(_forward())
        return {"ok": True}

    async def c_deregister_node(self, payload, conn):
        """Clean exit at the end of a drain: the node's entry goes DEAD
        immediately (no ghost DRAINING rows, no health-check wait) and its
        remaining actors fail over budget-free."""
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {"ok": False}
        await self._mark_node_dead(node, payload.get("reason", "drained (deregistered)"))
        client = self.node_clients.pop(node.node_id, None)
        if client is not None:
            try:
                await client.close()
            except Exception:
                pass
        return {"ok": True}

    # ---- drain object-relocation directory -----------------------------
    async def c_report_relocated(self, payload, conn):
        """Draining daemon reports shm objects it replicated to a peer:
        {moves: [{object_id, node_id, host, port}]}. Owners consult this
        (``get_relocated``) when their cached locations go stale."""
        self._wal_append("relocated", {"moves": payload["moves"]}, reply=True)
        for m in payload["moves"]:
            self.relocated_objects[m["object_id"]] = (
                m["node_id"], m["host"], m["port"],
            )
            self.relocated_objects.move_to_end(m["object_id"])
        while len(self.relocated_objects) > 65536:
            self.relocated_objects.popitem(last=False)
        return True

    async def c_get_relocated(self, payload, conn):
        loc = self.relocated_objects.get(payload["object_id"])
        if loc is None:
            return None
        return {"node_id": loc[0], "host": loc[1], "port": loc[2]}

    # ---- actors --------------------------------------------------------
    async def c_register_actor(self, payload, conn):
        spec: TaskSpec = payload["spec"]
        info = ActorInfo(spec=spec)
        self.actors[spec.actor_id] = info
        if spec.actor_name:
            key = (spec.namespace or "", spec.actor_name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing.state != "DEAD":
                    del self.actors[spec.actor_id]
                    raise ValueError(
                        f"actor name {spec.actor_name!r} already taken in "
                        f"namespace {spec.namespace!r}"
                    )
            self.named_actors[key] = spec.actor_id
        self._wal_append(
            "actor_register", {"spec": pickle.dumps(spec, protocol=5)},
            reply={"ok": True},
        )
        asyncio.ensure_future(self._schedule_actor(spec.actor_id))
        return {"ok": True}

    async def _schedule_actor(self, actor_id: ActorID) -> None:
        """GCS-direct actor scheduling (``GcsActorScheduler::ScheduleByGcs``)."""
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return
        deadline = time.monotonic() + GLOBAL_CONFIG.worker_lease_timeout_s
        while time.monotonic() < deadline:
            strategy = info.spec.scheduling_strategy
            pg_id = getattr(strategy, "pg_id", None)
            if pg_id is not None and pg_id not in self.pgs:
                # PG removed (or never created) while the actor was being
                # scheduled: fail fast instead of spinning to the lease
                # deadline with a misleading resource error.
                await self._finalize_actor_death(
                    actor_id,
                    f"placement group {pg_id.hex()[:12]} was removed before "
                    "the actor could be scheduled",
                )
                return
            node = pick_node_hybrid(
                self._alive_nodes(), info.spec.resources, strategy, self.pgs
            )
            if node is not None:
                client = self.node_clients[node.node_id]
                try:
                    result = await client.call(
                        "start_actor", {"spec": info.spec}, timeout=60
                    )
                    info.node_id = node.node_id
                    info.pid = result.get("pid", 0)
                    return  # worker will call actor_ready / actor_failed
                except Exception as e:
                    logger.warning("start_actor on %s failed: %r", node.node_id.hex()[:8], e)
            await asyncio.sleep(0.1)
        await self._finalize_actor_death(
            actor_id, f"no node can host actor (needs {info.spec.resources})"
        )

    def _alive_nodes(self) -> List[NodeInfo]:
        """Nodes eligible for NEW work: alive and not draining. (Draining
        nodes still serve running tasks/objects; they only leave the
        scheduling pool.)"""
        return [n for n in self.nodes.values() if n.alive and n.state != "DRAINING"]

    async def c_actor_ready(self, payload, conn):
        info = self.actors.get(payload["actor_id"])
        if info is None:
            return {"ok": False}
        info.address = payload["address"]
        info.state = "ALIVE"
        await self._publish(
            ACTOR_PUSH_CHANNEL,
            {"actor_id": payload["actor_id"], "state": "ALIVE", "address": info.address},
        )
        return {"ok": True}

    async def c_actor_creation_failed(self, payload, conn):
        await self._finalize_actor_death(
            payload["actor_id"], payload.get("reason", "creation failed"), creation_error=payload.get("error")
        )
        return {"ok": True}

    async def c_report_actor_death(self, payload, conn):
        # ``drained``: the reporting daemon was mid-drain — the death is
        # a preemption casualty, budget-free like the deregister failover
        await self._handle_actor_death(
            payload["actor_id"],
            payload.get("reason", "worker died"),
            drained=bool(payload.get("drained", False)),
        )
        return {"ok": True}

    async def _handle_actor_death(
        self, actor_id: ActorID, reason: str, drained: bool = False
    ) -> None:
        """The actor FSM restart edge (``gcs_actor_manager.h:548``).

        ``drained=True`` marks a death caused by a graceful node drain
        (preemption): restartable actors (``max_restarts != 0``) restart
        WITHOUT consuming budget — being preempted is not the actor's
        failure. Actors with ``max_restarts=0`` still die normally (their
        owners opted out of restarts; libraries like Train/Serve migrate
        them at their own layer during the drain window)."""
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return
        infinite = info.spec.max_restarts < 0  # -1 = restart forever
        budget_free = drained and info.spec.max_restarts != 0
        if (
            infinite or budget_free or info.num_restarts < info.spec.max_restarts
        ) and not self._stopping:
            if not budget_free:
                info.num_restarts += 1
                self._wal_append(
                    "actor_restart",
                    {"actor_id": pickle.dumps(actor_id, protocol=5),
                     "num_restarts": info.num_restarts},
                    reply={"ok": True},
                )
            info.state = "RESTARTING"
            info.address = None
            await self._publish(
                ACTOR_PUSH_CHANNEL, {"actor_id": actor_id, "state": "RESTARTING"}
            )
            logger.info(
                "restarting actor %s (%d/%d%s): %s",
                actor_id.hex()[:8], info.num_restarts, info.spec.max_restarts,
                " drained, budget-free" if budget_free else "", reason,
            )
            asyncio.ensure_future(self._schedule_actor(actor_id))
        else:
            await self._finalize_actor_death(actor_id, reason)

    async def _finalize_actor_death(self, actor_id: ActorID, reason: str, creation_error=None) -> None:
        info = self.actors.get(actor_id)
        if info is None:
            return
        info.state = "DEAD"
        info.death_reason = reason
        # DEAD actors leave the snapshot; the WAL records the death so a
        # replayed register+death nets out DEAD, not a ghost restart
        self._wal_append(
            "actor_death",
            {"actor_id": pickle.dumps(actor_id, protocol=5), "reason": reason},
            reply={"ok": True},
        )
        await self._publish(
            ACTOR_PUSH_CHANNEL,
            {"actor_id": actor_id, "state": "DEAD", "reason": reason, "error": creation_error},
        )

    async def c_kill_actor(self, payload, conn):
        actor_id = payload["actor_id"]
        info = self.actors.get(actor_id)
        if info is None:
            return {"ok": False}
        if payload.get("no_restart", True):
            info.spec.max_restarts = 0
        if payload.get("drain"):
            # graceful out-of-scope termination: restarts are now off and
            # the owner has enqueued __ray_terminate__ behind the actor's
            # pending calls — do NOT kill the worker here
            return {"ok": True}
        if info.address is not None and info.node_id in self.node_clients:
            try:
                await self.node_clients[info.node_id].call(
                    "kill_worker", {"pid": info.pid, "actor_id": actor_id}, timeout=5
                )
            except Exception:
                pass
        await self._handle_actor_death(actor_id, "killed via kill()")
        return {"ok": True}

    async def c_get_actor_info(self, payload, conn):
        info = self.actors.get(payload["actor_id"])
        if info is None:
            return None
        return {
            "state": info.state,
            "address": info.address,
            "reason": info.death_reason,
            "num_restarts": info.num_restarts,
            "max_concurrency": info.spec.max_concurrency,
            "max_task_retries": info.spec.max_task_retries,
        }

    async def c_get_named_actor(self, payload, conn):
        key = (payload.get("namespace") or "", payload["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return None
        return {
            "actor_id": actor_id,
            "method_opts": info.spec.method_opts,
            "owner": info.spec.owner,
            "max_concurrency": info.spec.max_concurrency,
        }

    async def c_list_named_actors(self, payload, conn):
        out = []
        for (ns, name), actor_id in self.named_actors.items():
            info = self.actors.get(actor_id)
            if info is None or info.state == "DEAD":
                continue
            if payload.get("all_namespaces") or ns == (payload.get("namespace") or ""):
                out.append({"name": name, "namespace": ns})
        return out

    # ---- placement groups ---------------------------------------------
    async def c_create_pg(self, payload, conn):
        pg_id: bytes = payload["pg_id"]
        info = PgInfo(
            pg_id=pg_id,
            bundles=payload["bundles"],
            strategy=payload["strategy"],
            name=payload.get("name", ""),
        )
        self.pgs[pg_id] = info
        if info.name:
            self.named_pgs[info.name] = pg_id
        self._wal_append(
            "pg_create",
            {"pg_id": pg_id, "bundles": info.bundles,
             "strategy": info.strategy, "name": info.name},
            reply={"ok": True},
        )
        asyncio.ensure_future(self._schedule_pg(pg_id))
        return {"ok": True}

    async def _schedule_pg(self, pg_id: bytes) -> None:
        """Bundle placement + 2-phase commit with the daemons
        (``GcsPlacementGroupScheduler`` + PACK/SPREAD/STRICT_* policies)."""
        info = self.pgs.get(pg_id)
        if info is None:
            return
        deadline = time.monotonic() + GLOBAL_CONFIG.worker_lease_timeout_s
        while time.monotonic() < deadline and not self._stopping:
            if self.pgs.get(pg_id) is not info:
                return  # removed while scheduling
            plan = place_bundles(self._alive_nodes(), info.bundles, info.strategy)
            if plan is not None:
                # phase 1: prepare on every node
                prepared: List[BundleReservation] = []
                ok = True
                for res in plan:
                    try:
                        await self.node_clients[res.node_id].call(
                            "prepare_bundle",
                            {"pg_id": pg_id, "bundle_index": res.bundle_index, "resources": res.resources},
                            timeout=10,
                        )
                        prepared.append(res)
                    except Exception as e:
                        logger.warning("prepare_bundle failed: %r", e)
                        ok = False
                        break
                if ok and self.pgs.get(pg_id) is not info:
                    ok = False  # removed mid-2PC: roll back the prepares
                if ok:
                    # phase 2: commit everywhere. A failed commit (node
                    # died between prepare and commit) releases everything
                    # and retries the whole placement — never wedge in
                    # PENDING with bundles leaked on surviving nodes.
                    try:
                        for res in plan:
                            await self.node_clients[res.node_id].call(
                                "commit_bundle",
                                {"pg_id": pg_id, "bundle_index": res.bundle_index, "resources": res.resources},
                                timeout=10,
                            )
                    except Exception as e:
                        logger.warning("commit_bundle failed: %r", e)
                        for res in plan:  # release both committed + prepared
                            try:
                                await self.node_clients[res.node_id].call(
                                    "release_bundle",
                                    {"pg_id": pg_id, "bundle_index": res.bundle_index},
                                    timeout=10,
                                )
                            except Exception:
                                pass
                        await asyncio.sleep(0.2)
                        continue
                    if self.pgs.get(pg_id) is not info:
                        # Removed between prepare and commit: release the
                        # now-orphaned bundles instead of leaking them.
                        for res in plan:
                            try:
                                await self.node_clients[res.node_id].call(
                                    "release_bundle",
                                    {"pg_id": pg_id, "bundle_index": res.bundle_index},
                                    timeout=10,
                                )
                            except Exception:
                                pass
                        return
                    info.reservations = plan
                    info.state = "CREATED"
                    await self._publish(PG_PUSH_CHANNEL, {"pg_id": pg_id, "state": "CREATED"})
                    return
                for res in prepared:  # rollback
                    try:
                        await self.node_clients[res.node_id].call(
                            "release_bundle", {"pg_id": pg_id, "bundle_index": res.bundle_index}, timeout=10
                        )
                    except Exception:
                        pass
            await asyncio.sleep(0.2)
        info.state = "INFEASIBLE"
        await self._publish(PG_PUSH_CHANNEL, {"pg_id": pg_id, "state": "INFEASIBLE"})

    async def c_remove_pg(self, payload, conn):
        pg_id = payload["pg_id"]
        info = self.pgs.get(pg_id)
        if info is None:
            return {"ok": False}
        for res in info.reservations:
            client = self.node_clients.get(res.node_id)
            if client is not None:
                try:
                    await client.call(
                        "release_bundle", {"pg_id": pg_id, "bundle_index": res.bundle_index}, timeout=10
                    )
                except Exception:
                    pass
        info.state = "REMOVED"
        if info.name:
            self.named_pgs.pop(info.name, None)
        self._wal_append("pg_remove", {"pg_id": pg_id}, reply={"ok": True})
        # Drop the table entry: long-lived clusters cycle many PGs and the
        # table would otherwise grow without bound. A bounded tombstone
        # lets racing clients tell "removed" apart from "never existed".
        self.pgs.pop(pg_id, None)
        self.removed_pgs[pg_id] = None
        while len(self.removed_pgs) > 4096:
            self.removed_pgs.popitem(last=False)
        await self._publish(PG_PUSH_CHANNEL, {"pg_id": pg_id, "state": "REMOVED"})
        return {"ok": True}

    async def c_get_pg(self, payload, conn):
        info = self.pgs.get(payload["pg_id"])
        if info is None:
            if payload["pg_id"] in self.removed_pgs:
                return {
                    "state": "REMOVED",
                    "bundles": [],
                    "strategy": "",
                    "nodes": [],
                    "bundle_indices": [],
                }
            return None
        return {
            "state": info.state,
            "bundles": info.bundles,
            "strategy": info.strategy,
            "nodes": [r.node_id for r in info.reservations],
            "bundle_indices": [r.bundle_index for r in info.reservations],
        }

    async def c_get_named_pg(self, payload, conn):
        pg_id = self.named_pgs.get(payload["name"])
        if pg_id is None:
            return None
        info = self.pgs.get(pg_id)
        return {"pg_id": pg_id, "bundles": info.bundles, "state": info.state}

    async def c_pg_table(self, payload, conn):
        return {
            pg_id.hex(): {
                "state": info.state,
                "bundles": info.bundles,
                "strategy": info.strategy,
                "name": info.name,
            }
            for pg_id, info in self.pgs.items()
        }

    # ---- observability --------------------------------------------------
    async def c_worker_logs(self, payload, conn):
        """Daemon-forwarded worker log lines → broadcast to drivers
        (reference LogMonitor → GCS pubsub → driver)."""
        await self._publish(
            LOG_PUSH_CHANNEL,
            {"node_id": payload["node_id"], "batch": payload["batch"]},
        )
        return True

    async def c_task_events(self, payload, conn):
        """Batched task lifecycle events (``GcsTaskManager`` sink).

        Each event: {task_id, name, state, worker?, ts}; the latest state
        per task wins; the table is a bounded ring."""
        rank = {"SUBMITTED": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 2}
        for ev in payload["events"]:
            tid = ev["task_id"]
            cur = self.task_events.get(tid)
            if cur is None:
                self.task_events[tid] = ev
            else:
                # never downgrade: a worker's late-flushed RUNNING must
                # not overwrite the driver's FINISHED (batch windows race)
                if rank.get(ev["state"], 0) >= rank.get(cur["state"], 0):
                    cur.update(ev)
                self.task_events.move_to_end(tid)
        while len(self.task_events) > 10000:
            self.task_events.popitem(last=False)
        return True

    async def c_list_tasks(self, payload, conn):
        limit = payload.get("limit", 1000)
        if limit <= 0:
            return []
        out = []
        for ev in list(self.task_events.values())[-limit:]:
            out.append(dict(ev, task_id=ev["task_id"].hex()))
        return out

    async def c_list_actors(self, payload, conn):
        return [
            {
                "actor_id": actor_id.hex(),
                "name": info.spec.name,
                "class_name": info.spec.method_name or info.spec.name,
                "state": info.state,
                "pid": info.pid,
                "node_id": info.node_id.hex() if info.node_id else None,
                "num_restarts": info.num_restarts,
            }
            for actor_id, info in self.actors.items()
        ]

    async def c_list_objects(self, payload, conn):
        """Cluster-wide shm object listing, aggregated from daemons
        (concurrent fan-out: N sequential 10s timeouts would stall the
        control loop on dead nodes)."""
        items = list(self.node_clients.items())

        async def one(client):
            try:
                return await client.call("list_objects", {}, timeout=10)
            except Exception:
                return []

        results = await asyncio.gather(*[one(c) for _nid, c in items])
        out = []
        for (node_id, _c), objs in zip(items, results):
            for o in objs:
                o["node_id"] = node_id.hex()
                out.append(o)
        return out

    async def c_cluster_telemetry(self, payload, conn):
        """Federated cluster telemetry (RPC flavor of /federate): the
        controller's own registry plus every node's, as raw exposition
        text per source. ``federate_port`` is the HTTP port serving the
        merged node-labeled view."""
        from ray_tpu.observability.metrics import render

        loop = asyncio.get_event_loop()
        items = list(self.node_clients.items())

        async def one(client: RpcClient):
            try:
                return await client.call("metrics_text", {}, timeout=10)
            except Exception:
                return None

        texts = await asyncio.gather(*[one(c) for _nid, c in items])
        return {
            "controller": await loop.run_in_executor(None, render),
            "nodes": {
                node_id.hex(): text
                for (node_id, _c), text in zip(items, texts)
                if text is not None
            },
            "federate_port": self.metrics_port,
        }

    async def c_cluster_status(self, payload, conn):
        """Live cluster state in one reply (the ``ray list`` equivalent)
        from tables the controller already keeps bounded: node
        membership, actors, a task-state summary + recent tail, per-node
        object-store stats (refreshed by every resource sync), placement
        groups, and jobs. Serve replicas appear in ``actors`` — replica
        liveness is actor liveness."""
        limit = (payload or {}).get("recent_tasks", 20)
        task_summary: Dict[str, int] = {}
        for ev in self.task_events.values():
            task_summary[ev["state"]] = task_summary.get(ev["state"], 0) + 1
        return {
            # control-plane durability/failover facts: incarnation
            # epoch, whether this incarnation is a promoted standby, and
            # the recovery report (operators verify a takeover restored
            # the WAL tip — wal_records > 0 — not just a stale snapshot)
            "controller": {
                "epoch": self.epoch,
                "takeover": self.takeover,
                "recovery": dict(self.recovery_report),
                "wal_appends": self._wal.appended if self._wal is not None else 0,
            },
            "nodes": await self.c_nodes(None, conn),
            "actors": await self.c_list_actors(None, conn),
            "tasks": {
                "summary": task_summary,
                "recent": [
                    dict(ev, task_id=ev["task_id"].hex())
                    for ev in list(self.task_events.values())[-limit:]
                ],
            },
            "objects": {
                n.node_id.hex(): dict(
                    n.store_stats,
                    num_workers=n.num_workers,
                    num_leases=n.num_leases,
                )
                for n in self.nodes.values()
                if n.alive
            },
            "placement_groups": await self.c_pg_table(None, conn),
            "jobs": [
                {
                    "job_id": jid.hex() if isinstance(jid, bytes) else str(jid),
                    "start_time": info.get("start_time"),
                    "driver_pid": info.get("driver_pid"),
                }
                for jid, info in self.jobs.items()
            ],
        }

    # ---- timeline event exports (bounded; observability/timeline.py) ----
    async def c_export_events(self, payload, conn):
        """Worker-exported timeline chunk. Keyed by the exporter's
        unique (uid, pid, chunk) key — a retried export overwrites its
        own entry (idempotent). Retention: oldest chunks are dropped
        past ``timeline_kv_max_bytes`` (a single oversized chunk is
        kept while alone), and a node's chunks die with it."""
        key = payload["key"]
        if isinstance(key, bytes):
            key = key.decode()
        blob = payload["blob"]
        old = self.timeline_exports.pop(key, None)
        if old is not None:
            self._timeline_export_bytes -= len(old[1])
        self.timeline_exports[key] = (payload.get("node_id") or b"", blob)
        self._timeline_export_bytes += len(blob)
        budget = GLOBAL_CONFIG.timeline_kv_max_bytes
        while (
            self._timeline_export_bytes > budget
            and len(self.timeline_exports) > 1
        ):
            _k, (_nid, old_blob) = self.timeline_exports.popitem(last=False)
            self._timeline_export_bytes -= len(old_blob)
        return True

    async def c_collect_events(self, payload, conn):
        """Driver-side ``timeline()`` pulls every retained chunk."""
        return [blob for (_nid, blob) in self.timeline_exports.values()]

    # ---- kv ------------------------------------------------------------
    async def c_kv_put(self, payload, conn):
        self._wal_append(
            "kv_put", {"key": payload["key"], "value": payload["value"]},
            reply=True,
        )
        self.kv[payload["key"]] = payload["value"]
        return True

    async def c_kv_get(self, payload, conn):
        return self.kv.get(payload["key"])

    async def c_kv_del(self, payload, conn):
        existed = payload["key"] in self.kv
        if existed:
            self._wal_append("kv_del", {"key": payload["key"]}, reply=True)
            self.kv.pop(payload["key"], None)
        return existed

    async def c_kv_keys(self, payload, conn):
        prefix = payload.get("prefix", b"")
        return [k for k in self.kv if k.startswith(prefix)]

    # ---- jobs ----------------------------------------------------------
    async def c_register_job(self, payload, conn):
        info = {"start_time": time.time(), **payload}
        self._wal_append(
            "job_register",
            {"job_id": payload["job_id"],
             "info": pickle.dumps(info, protocol=5)},
            reply=True,
        )
        self.jobs[payload["job_id"]] = info
        return True

    async def c_ping(self, payload, conn):
        return "pong"

    async def c_event_stats(self, payload, conn):
        """Debug state (reference DebugString + event_stats.h): per-handler
        timing plus loop-lag/stall counters of THIS process's loops."""
        from ray_tpu.observability.event_stats import debug_snapshot

        return debug_snapshot()
