"""Standalone controller process (control-plane failover topology).

Reference: ``gcs_server_main.cc`` — the GCS runs as its own process so
it can be killed and restarted independently of any raylet. The default
local topology co-hosts controller + head daemon in one process
(``head_main.py``); THIS entrypoint exists for deployments (and the
controller-failover tests) where the control plane must be able to die
and come back from its snapshot while every node daemon, worker, and
driver stays up and reconnects.

On restart with the same ``--session-dir``, ``Controller._load_snapshot``
restores the KV / job / PG / actor tables AND the old listening port,
``Controller._open_and_replay_wal`` replays every mutation acked since
the last snapshot tick (core/wal.py), so existing clients reconnect to
the same address with no rediscovery and no loss window; daemons
re-register (carrying held bundles and running actors for re-adoption)
the moment their next resource sync returns ``unknown_node``.

``--standby`` runs the HOT-STANDBY topology instead: the process tails
the shared session dir's WAL (warming the page cache toward the tip)
and watches the active's lease heartbeats; when the lease goes stale —
crash — or is released (``ts=0``, clean shutdown), it replays snapshot +
WAL to the tip, takes a strictly higher incarnation epoch, announces
itself to every known daemon (fencing the old epoch cluster-wide), and
rebinds the old port. Sub-second-after-lease-expiry failover versus an
operator-driven restart.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys
import time

logger = logging.getLogger("ray_tpu.controller_main")


async def _standby_wait(session_dir: str, stop: asyncio.Event) -> bool:
    """Follower loop: poll the lease + tail the WAL until the lease goes
    stale/released (return True = promote) or ``stop`` fires (False)."""
    from ray_tpu.core import wal as walmod
    from ray_tpu.core.config import GLOBAL_CONFIG

    lease_path = os.path.join(session_dir, "controller.lease")
    wal_path = os.path.join(session_dir, "controller.wal")
    interval = GLOBAL_CONFIG.controller_lease_interval_s
    timeout = GLOBAL_CONFIG.controller_lease_timeout_s
    tail_offset, tail_records = 0, 0
    ever_saw_lease = False
    while not stop.is_set():
        lease = walmod.read_lease(lease_path)
        if lease is not None:
            ever_saw_lease = True
            ts = lease.get("ts", 0.0)
            if ts == 0.0:
                logger.info("active released the lease (clean stop): promoting")
                return True
            if time.time() - ts > timeout:
                logger.warning(
                    "lease stale by %.2fs (epoch %d, pid %d): promoting",
                    time.time() - ts, lease.get("epoch", 0), lease.get("pid", 0),
                )
                return True
        elif ever_saw_lease:
            # lease file vanished after being held — treat as released
            return True
        tail_offset, n = walmod.scan_tip(wal_path, tail_offset)
        tail_records += n
        if n:
            logger.debug("tailed %d WAL records (total %d)", n, tail_records)
        try:
            await asyncio.wait_for(stop.wait(), timeout=interval)
        except asyncio.TimeoutError:
            pass
    return False


async def amain(args) -> None:
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.controller import Controller

    if args.system_config:
        GLOBAL_CONFIG.apply_system_config(json.loads(args.system_config))
    persist = None
    if args.session_dir:
        os.makedirs(args.session_dir, exist_ok=True)
        persist = os.path.join(args.session_dir, "controller_snapshot.pkl")

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    # driver-owned controllers die with their driver (hang defense);
    # detached CLI deployments never set the env var and survive
    from ray_tpu.util.reaper import start_orphan_watch

    start_orphan_watch(lambda: loop.call_soon_threadsafe(stop.set))

    takeover = False
    if args.standby:
        if not args.session_dir:
            raise SystemExit("--standby requires --session-dir")
        # handshake immediately (the spawner is blocked on this line):
        # report the port the ACTIVE currently serves — the one this
        # standby will rebind on takeover
        from ray_tpu.core import wal as walmod

        lease = walmod.read_lease(
            os.path.join(args.session_dir, "controller.lease")
        )
        print(json.dumps({
            "controller_port": (lease or {}).get("port", 0),
            "standby": True,
        }), flush=True)
        if not await _standby_wait(args.session_dir, stop):
            return  # stopped while still a follower
        takeover = True
        if not args.port:
            # rebind the port the active served on (the lease carries
            # it even when no snapshot tick ever recorded one)
            lease = walmod.read_lease(
                os.path.join(args.session_dir, "controller.lease")
            )
            args.port = (lease or {}).get("port", 0)

    controller = Controller(port=args.port, persist_path=persist,
                            takeover=takeover)
    # a deposed incarnation (higher epoch claimed the lease) must exit so
    # its successor can rebind the port — trip the process stop event
    controller.on_deposed = lambda: loop.call_soon_threadsafe(stop.set)
    cport = await controller.start()
    if takeover:
        from ray_tpu.observability.rpc_metrics import CONTROLLER_TAKEOVERS

        CONTROLLER_TAKEOVERS.inc()
        logger.warning(
            "standby promoted: epoch=%d port=%d recovery=%r",
            controller.epoch, cport, controller.recovery_report,
        )
    else:
        print(json.dumps({"controller_port": cport}), flush=True)

    await stop.wait()
    await controller.stop()


def main() -> None:
    import faulthandler

    faulthandler.enable()
    faulthandler.register(signal.SIGUSR2, all_threads=True)
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-dir", type=str, default=None)
    parser.add_argument("--system-config", type=str, default="")
    parser.add_argument("--standby", action="store_true",
                        help="run as a hot standby tailing the session "
                             "dir's WAL; promote on lease expiry")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
