"""Standalone controller process (control-plane failover topology).

Reference: ``gcs_server_main.cc`` — the GCS runs as its own process so
it can be killed and restarted independently of any raylet. The default
local topology co-hosts controller + head daemon in one process
(``head_main.py``); THIS entrypoint exists for deployments (and the
controller-failover tests) where the control plane must be able to die
and come back from its snapshot while every node daemon, worker, and
driver stays up and reconnects.

On restart with the same ``--session-dir``, ``Controller._load_snapshot``
restores the KV / job / PG / actor tables AND the old listening port, so
existing clients reconnect to the same address with no rediscovery;
daemons re-register (carrying held bundles and running actors for
re-adoption) the moment their next resource sync returns
``unknown_node``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys


async def amain(args) -> None:
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.controller import Controller

    if args.system_config:
        GLOBAL_CONFIG.apply_system_config(json.loads(args.system_config))
    persist = None
    if args.session_dir:
        os.makedirs(args.session_dir, exist_ok=True)
        persist = os.path.join(args.session_dir, "controller_snapshot.pkl")
    controller = Controller(port=args.port, persist_path=persist)
    cport = await controller.start()
    print(json.dumps({"controller_port": cport}), flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    # driver-owned controllers die with their driver (hang defense);
    # detached CLI deployments never set the env var and survive
    from ray_tpu.util.reaper import start_orphan_watch

    start_orphan_watch(lambda: loop.call_soon_threadsafe(stop.set))
    await stop.wait()
    await controller.stop()


def main() -> None:
    import faulthandler

    faulthandler.enable()
    faulthandler.register(signal.SIGUSR2, all_threads=True)
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-dir", type=str, default=None)
    parser.add_argument("--system-config", type=str, default="")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
