"""CoreWorker: the per-process worker library for cluster mode.

Reference: ``src/ray/core_worker/`` — the library linked into every worker
and driver (``core_worker.h:163``): object put/get/wait against the dual
store (in-process memory store + node shm store), normal-task submission
through the raylet lease protocol with spillback
(``transport/normal_task_submitter.h:108``), per-actor ordered submission
with restart handling (``transport/actor_task_submitter``), the execution
callback path (``HandlePushTask``, ``core_worker.cc:3617``), and the
owner services (object status, borrower registration) backing the
ownership model.

One CoreWorker instance implements ``RuntimeBackend``, so drivers and
workers share every code path; workers additionally run a ``TaskExecutor``
(see ``task_executor.py``) behind their ``push_task`` service.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.api import RuntimeBackend
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.controller import (
    ACTOR_PUSH_CHANNEL,
    LOG_PUSH_CHANNEL,
    NODE_PUSH_CHANNEL,
    PG_PUSH_CHANNEL,
)
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    OwnerDiedError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import MemoryStore, StoreClient
from ray_tpu.core.object_store import segment_name as _segment_name
from ray_tpu.core.ownership import ObjState, ReferenceCounter
from ray_tpu.core.refs import Address, ObjectRef
from ray_tpu.core.rpc import (
    ChaosInjectedError,
    ConnectionLost,
    IoThread,
    RpcClient,
    RpcServer,
)
from ray_tpu.core.task_spec import TaskKind, TaskSpec, encode_spec
from ray_tpu.observability import timeline as _timeline
from ray_tpu.observability import tracing as _tracing

logger = logging.getLogger(__name__)


def _loop_event_setter(loop, ev: "asyncio.Event"):
    """Completion callback that sets an asyncio.Event from ANY thread:
    plain set() when already on the target loop (the common case — reply
    processing runs there, and call_soon_threadsafe's self-pipe write is
    a ~1ms syscall under load), threadsafe wakeup otherwise."""

    def cb():
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            ev.set()
        else:
            loop.call_soon_threadsafe(ev.set)

    return cb


class _ClassQueue:
    """Pending normal tasks of one scheduling class + active pump count."""

    __slots__ = ("specs", "pumps", "work")

    def __init__(self):
        import asyncio as _asyncio
        from collections import deque

        self.specs = deque()
        self.pumps = 0
        self.work = _asyncio.Event()  # set on enqueue: wakes lingering pumps


class _ActorState:
    def __init__(self):
        self.state: str = "PENDING"
        self.address: Optional[Address] = None
        self.reason: str = ""
        self.max_task_retries: int = 0
        self.max_concurrency: int = 1
        self.creation_spec = None  # pins implicit-put creation args
        self.event = threading.Event()  # set whenever state changes


class CoreWorker(RuntimeBackend):
    def __init__(
        self,
        controller_host: str,
        controller_port: int,
        daemon_host: str,
        daemon_port: int,
        *,
        io: Optional[IoThread] = None,
        executor=None,  # TaskExecutor for worker processes
    ):
        self.io = io or IoThread()
        self.executor = executor
        self.worker_id = WorkerID.from_random()
        self.memory = MemoryStore()
        self.shm = StoreClient()
        self.refcounter = ReferenceCounter(self._on_free)
        self.node_id: bytes = b""
        self.daemon_addr = (daemon_host, daemon_port)
        self.address: Optional[Address] = None
        self._actors: Dict[ActorID, _ActorState] = {}
        self._actors_lock = threading.Lock()
        #: highest controller incarnation epoch seen on state pushes —
        #: pushes stamped lower come from a deposed controller racing
        #: its own takeover and are dropped (worker half of fencing)
        self._controller_epoch_seen = 0
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._pg_states: Dict[bytes, str] = {}
        self._pg_events: Dict[bytes, threading.Event] = {}
        self._actor_queues: Dict[ActorID, Any] = {}
        self._pump_tasks: List[Any] = []
        self._stopping = False
        # cancellation state (``CoreWorker::CancelTask``): task ids marked
        # cancelled + where each inflight normal task currently executes.
        # Bounded FIFO: cancels of actor tasks / already-freed refs have no
        # finalize path to reclaim their entries.
        self._cancelled_tasks: "OrderedDict[bytes, None]" = OrderedDict()
        self._inflight_workers: Dict[bytes, Tuple[str, int]] = {}
        # lease-reuse submission (per scheduling class)
        self._class_queues: Dict[Any, "_ClassQueue"] = {}
        self._retries_left: Dict[bytes, int] = {}
        # submit batching: specs buffer on the caller thread and drain in
        # one loop callback — call_soon_threadsafe once per burst instead
        # of run_coroutine_threadsafe (a new Task) per task.
        self._submit_buf: List[Tuple[bool, TaskSpec]] = []
        self._submit_lock = threading.Lock()
        self._submit_scheduled = False
        # streaming generators (``task_manager.h:102`` ObjectRefStream).
        # Locked: item pushes land on the io loop while abandon runs on
        # the consumer/GC thread — an unordered pop could leak the hold
        # created for an in-flight item.
        self._streams: Dict[bytes, Any] = {}
        self._streams_lock = threading.Lock()
        # node membership/drain event listeners (Train drain watch etc.)
        self._node_event_listeners: List[Any] = []
        # nodes the controller has pushed as dead: fetches skip these
        # sources and go straight to the relocation directory instead of
        # burning the chunk-retry ladder against a corpse
        self._dead_nodes: set = set()
        # borrowed refs observed ready via a status RPC: lets a
        # wait(timeout=0) poll answer from cache instead of paying the
        # borrowed-status grace window every call (bounded FIFO)
        self._borrowed_ready: "OrderedDict[bytes, None]" = OrderedDict()
        # executor-side cache of task-spec templates (template_id →
        # SpecTemplate): pushes carry (template_id, per-call fields);
        # the full invariant prefix is fetched from the KV once
        self._tmpl_cache: Dict[bytes, Any] = {}
        # task-event buffer (``core_worker/task_event_buffer`` →
        # ``GcsTaskManager``): batched lifecycle events for `list tasks`.
        # Locked: emitters run on lane/user threads, the flusher swaps the
        # list on the io loop — an unguarded append could land on an
        # already-sent list and silently vanish.
        self._task_events: List[Dict[str, Any]] = []
        self._task_events_lock = threading.Lock()
        self._task_events_flushing = False
        # blocked-worker resource release (satellite of the zero-copy
        # data plane PR; reference NotifyDirectCallTaskBlocked): worker
        # processes tell their daemon when a get is about to PARK so the
        # daemon can lend the held CPUs out, and again on wake. Depth-
        # counted — concurrent lane threads blocking notify once.
        self._spawn_token = (
            os.environ.get("RAY_TPU_SPAWN_TOKEN", "") if executor is not None else ""
        )
        self._blocked_depth = 0
        self._blocked_lock = threading.Lock()

        async def _setup():
            self.server = RpcServer()
            for name in [m for m in dir(self) if m.startswith("w_")]:
                self.server.register(name[2:], getattr(self, name))
            port = await self.server.start()
            # retry-by-default toward the control plane: mutating calls
            # are dedup-stamped (core/rpc.py), so a controller restart or
            # a lost reply is a transparent retry, never a duplicate
            self.controller = RpcClient(
                controller_host, controller_port, name="controller",
                default_retries=GLOBAL_CONFIG.rpc_max_retries,
                role="controller",
            )
            self.daemon = RpcClient(
                daemon_host, daemon_port, name="noded", role="noded"
            )
            self.controller.subscribe_push(ACTOR_PUSH_CHANNEL, self._on_actor_push)
            self.controller.subscribe_push(PG_PUSH_CHANNEL, self._on_pg_push)
            self.controller.subscribe_push(NODE_PUSH_CHANNEL, self._on_node_push)
            channels = [ACTOR_PUSH_CHANNEL, PG_PUSH_CHANNEL, NODE_PUSH_CHANNEL]
            if executor is None and GLOBAL_CONFIG.log_to_driver:
                # drivers print forwarded worker logs (reference
                # LogMonitor → pubsub → driver stdout); workers never
                # subscribe the log channel, so the controller doesn't
                # waste pushes on processes that would drop them
                self.controller.subscribe_push(LOG_PUSH_CHANNEL, self._on_log_push)
                channels.append(LOG_PUSH_CHANNEL)
            # push subscriptions are per-connection server-side: a
            # controller restart silently drops them, so re-subscribe on
            # every reconnect (reconnect-and-reconcile)
            self._push_channels = channels
            self.controller.on_reconnect = self._on_controller_reconnect
            await self.controller.call(
                "subscribe",
                {"channels": channels},
                retries=GLOBAL_CONFIG.rpc_max_retries,
            )
            return port

        self.port = self.io.run(_setup())
        self.host = "127.0.0.1"

    async def _on_controller_reconnect(self) -> None:
        """The controller connection was re-established (restart or
        transient reset): re-subscribe push channels — server-side
        subscription state died with the old connection."""
        from ray_tpu.observability.rpc_metrics import CONTROLLER_RECONNECTS

        CONTROLLER_RECONNECTS.inc(
            labels={"role": "worker" if self.executor is not None else "driver"}
        )
        if self._stopping:
            return
        await self.controller.call(
            "subscribe",
            {"channels": self._push_channels},
            retries=GLOBAL_CONFIG.rpc_max_retries,
        )

    def finish_init(self, node_id: bytes) -> None:
        self.node_id = node_id
        self.address = Address(
            worker_id=self.worker_id.binary(),
            node_id=node_id,
            host=self.host,
            port=self.port,
        )

    # ------------------------------------------------------------------
    # client cache
    def _client(self, host: str, port: int, role: Optional[str] = None) -> RpcClient:
        """Cached peer client. ``role`` tags the SERVER's role for the
        per-role idempotent-method classification (core/rpc.py) — one
        address is one server, so a later tagged lookup may upgrade an
        untagged cache entry, never flip an existing tag."""
        key = (host, port)
        c = self._clients.get(key)
        if c is None:
            c = self._clients[key] = RpcClient(
                host, port, name=f"peer-{port}", role=role
            )
            # stream items ride back over the submission connection
            from ray_tpu.core.streaming import STREAM_PUSH_CHANNEL

            c.subscribe_push(STREAM_PUSH_CHANNEL, self._on_stream_item)
        elif c.role is None and role is not None:
            c.role = role
        return c

    def _owner_client(self, ref: ObjectRef) -> RpcClient:
        addr = ref.owner_address
        if addr is None:
            raise OwnerDiedError(ref.id(), "ref has no owner address")
        return self._client(addr.host, addr.port, role="worker")

    # ------------------------------------------------------------------
    # objects: put
    def put_object(self, object_id: ObjectID, ser: serialization.SerializedValue) -> None:
        if ser.total_bytes <= GLOBAL_CONFIG.max_direct_call_object_size:
            data = ser.to_bytes()
            self.memory.put(object_id, data)
            self.refcounter.create_inline(
                object_id, data, contained=ser.contained_refs, hold=True
            )
        else:
            size = self.shm.create_and_write(object_id, ser)
            self.io.run(self.daemon.call("adopt_object", {"object_id": object_id.binary(), "size": size}))
            self.refcounter.create_at_location(
                object_id, self._self_location(), contained=ser.contained_refs, hold=True
            )

    def _self_location(self) -> tuple:
        return (self.node_id, self.daemon_addr[0], self.daemon_addr[1])

    # ------------------------------------------------------------------
    # objects: get
    def get_objects(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        # Tracing wrapper: a get() on a traced result (or inside a traced
        # task) records a "get" span closing the submit → execute →
        # result-push → get chain. The unsampled hot path pays one float
        # compare + one contextvar read and goes straight to the inner
        # body — no lineage lookup, no timestamping.
        if GLOBAL_CONFIG.trace_sample_rate <= 0.0 and _tracing.current() is None:
            return self._get_objects_inner(refs, timeout)
        wire = _tracing.current_wire()
        if wire is None and refs:
            obj = self.refcounter.get(refs[0].id())
            lineage = getattr(obj, "lineage", None)
            wire = getattr(lineage, "trace_ctx", None)
        if wire is None:
            return self._get_objects_inner(refs, timeout)
        t0_us = _timeline._now_us()
        try:
            return self._get_objects_inner(refs, timeout)
        finally:
            _tracing.record_span(
                wire, f"get::{len(refs)}", t0_us, _timeline._now_us(),
                category="task",
            )

    def _worker_blocked_scope(self):
        """Context manager bracketing a blocking wait inside a WORKER
        process: on entry (outermost only) the daemon releases the CPU
        share of this worker's lease so other tasks — e.g. the producer
        this get waits on — can run; on exit it re-acquires. No-op for
        drivers and when disabled. Notification loss is safe: the daemon
        self-heals accounting at lease release."""
        import contextlib

        if not self._spawn_token or not GLOBAL_CONFIG.blocked_worker_resource_release:
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def scope():
            with self._blocked_lock:
                self._blocked_depth += 1
                notify = self._blocked_depth == 1
            if notify:
                self._notify_daemon_blocked("worker_blocked")
            try:
                yield
            finally:
                with self._blocked_lock:
                    self._blocked_depth -= 1
                    notify = self._blocked_depth == 0
                if notify:
                    self._notify_daemon_blocked("worker_unblocked")

        return scope()

    def _notify_daemon_blocked(self, method: str) -> None:
        try:
            self.io.run(
                self.daemon.call(method, {"token": self._spawn_token}, timeout=5),
                timeout=10,
            )
        except Exception:
            logger.debug("%s notification failed", method, exc_info=True)

    def _get_objects_inner(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        # Sync fast path for owned refs: resolve on the CALLING thread —
        # in-process cache hits return immediately, pending results park
        # on the ownership table's threading waiters. The io loop stays
        # free to process completions (it paid ~70µs of task/event/timer
        # machinery per ref in the async path, plus two cross-thread
        # wakeups per get() call). Borrowed refs and shm-resident values
        # drop to the async path (owner RPCs / store fetches live there).
        out: List[Any] = []
        for i, r in enumerate(refs):
            oid = r.id()
            data = self.memory.get(oid)
            if data is not None:
                out.append(serialization.deserialize_bytes(data))
                continue
            if not self.refcounter.owns(oid):
                break  # borrowed: async path handles the owner protocol
            remaining = (
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            obj = self.refcounter.get(oid)
            if obj is not None and obj.ready():
                pass  # no park coming: skip the blocked notification
            else:
                # about to PARK this worker thread: lend the held CPUs
                # out for the duration (deadlock defense — the producer
                # we wait on may need them)
                with self._worker_blocked_scope():
                    obj = self.refcounter.wait_ready(oid, remaining)
            if obj is None or not obj.ready():
                raise GetTimeoutError(f"get() timed out waiting for {oid.hex()[:12]}")
            if obj.state == ObjState.FAILED:
                out.append(obj.error)
            elif obj.inline is not None:
                out.append(serialization.deserialize_bytes(obj.inline))
            else:
                # shm-resident result: hand this ref AND the rest to the
                # async path so node-to-node fetches (and any lineage
                # recovery) overlap instead of running serially here
                break
        else:
            return out
        rest = list(refs[i:])

        async def _get_all():
            return await asyncio.gather(*[self._get_one(r, deadline) for r in rest])

        # the async path may fetch across nodes / wait on borrowed
        # owners: treat it as a potential park (the lend/re-acquire pair
        # costs two sub-ms daemon RPCs, noise next to any real fetch)
        with self._worker_blocked_scope():
            return out + self.io.run(_get_all())

    async def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.id()
        data = self.memory.get(oid)
        if data is not None:
            return serialization.deserialize_bytes(data)
        if self.refcounter.owns(oid):
            return await self._get_owned(ref, deadline)
        return await self._get_borrowed(ref, deadline)

    async def _await_owned_ready(self, oid: ObjectID, deadline: Optional[float]):
        """Event-driven completion wait on the io loop — no executor-thread
        dispatch per ref (a 200-ref get would otherwise pay 200 thread
        round-trips)."""
        obj = self.refcounter.get(oid)
        if obj is not None and obj.ready():
            return obj
        loop = asyncio.get_event_loop()
        ev = asyncio.Event()
        cb = _loop_event_setter(loop, ev)
        if not self.refcounter.on_ready(oid, cb):
            try:
                timeout = (
                    None if deadline is None else max(0.0, deadline - time.monotonic())
                )
                await asyncio.wait_for(ev.wait(), timeout)
            except (asyncio.TimeoutError, TimeoutError):
                pass
            finally:
                self.refcounter.remove_ready_callback(oid, cb)
        return self.refcounter.get(oid)

    async def _get_owned(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.id()
        while True:
            obj = await self._await_owned_ready(oid, deadline)
            if obj is None or not obj.ready():
                raise GetTimeoutError(f"get() timed out waiting for {oid.hex()[:12]}")
            if obj.state == ObjState.FAILED:
                return obj.error
            if obj.inline is not None:
                return serialization.deserialize_bytes(obj.inline)
            locations = list(obj.locations)
            try:
                return await self._fetch_from_locations(oid, locations, deadline)
            except ObjectLostError:
                # Every copy is gone (node death): reconstruct from lineage
                # by resubmitting the producing task, then wait again. The
                # observed set guards against destroying a copy created by
                # a recovery that completed while we were fetching.
                if not self._try_recover(oid, observed_locations=locations):
                    raise

    async def _get_borrowed(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.id()
        owner = self._owner_client(ref)
        while True:
            step = 30.0
            if deadline is not None:
                step = min(step, max(0.0, deadline - time.monotonic()))
            try:
                status = await owner.call(
                    "get_object_status",
                    {"object_id": oid.binary(), "timeout": step},
                    timeout=step + 10,
                )
            except ConnectionLost:
                raise OwnerDiedError(oid, "owner process is gone")
            kind = status["status"]
            if kind == "inline":
                data = status["data"]
                self.memory.put(oid, data)  # borrower-side cache
                return serialization.deserialize_bytes(data)
            if kind == "locations":
                try:
                    return await self._fetch_from_locations(oid, status["locations"], deadline)
                except ObjectLostError:
                    # Ask the owner to reconstruct, then re-poll status.
                    try:
                        recovered = await owner.call(
                            "recover_object",
                            {"object_id": oid.binary(), "observed": status["locations"]},
                            timeout=30,
                        )
                    except ConnectionLost:
                        raise OwnerDiedError(oid, "owner died during recovery")
                    if not recovered:
                        raise
                    continue
            if kind == "error":
                return pickle.loads(status["error"])
            if kind == "unknown":
                raise ObjectLostError(oid, "owner does not know this object (freed?)")
            # pending → loop unless out of time
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(f"get() timed out waiting for {oid.hex()[:12]}")

    @staticmethod
    def _parse_pull_reply(reply):
        """Split a ``pull_object`` reply into (meta, failure): success is
        the ``{"segment", "size"}`` meta; a structured failure carries
        ``no_source`` + per-source ``causes`` (see core/pull_manager.py).
        A bare None (legacy daemon) maps to an empty failure."""
        if reply is None:
            return None, {"failed": True, "no_source": True, "causes": {}}
        if isinstance(reply, dict) and reply.get("failed"):
            return None, reply
        return reply, None

    async def _fetch_from_locations(self, oid: ObjectID, locations, deadline) -> Any:
        """Materialize a shm object locally, then zero-copy deserialize."""
        from ray_tpu.core.deadline import effective_timeout

        if not locations:
            raise ObjectLostError(oid, "no locations")
        local = next((l for l in locations if l[0] == self.node_id), None)
        if local is not None:
            meta = await self.daemon.call("get_object_meta", {"object_id": oid.binary()})
        else:
            meta = None
        failure = None
        skipped_dead_sources = False
        if meta is None:
            sources = [(h, p) for (_nid, h, p) in locations if _nid != self.node_id]
            live = [
                (h, p)
                for (_nid, h, p) in locations
                if _nid != self.node_id and _nid not in self._dead_nodes
            ]
            skipped_dead_sources = len(live) < len(sources)
            if sources and not live:
                # every remote holder is controller-confirmed DEAD: a pull
                # would only burn its chunk-retry ladder against corpses.
                # Skip straight to the relocation consult (drained nodes
                # replicate primaries away before exiting); if the
                # directory has nothing we still try the stale sources
                # below, so a spurious dead-marking can't lose an object.
                failure = {"failed": True, "no_source": True, "causes": {}}
            else:
                # the pull inherits this get()'s remaining budget (nested
                # gets propagate deadlines through the whole fetch path —
                # a hard-coded 300 here used to quietly extend the caller's)
                budget = effective_timeout(300.0)
                reply = await self.daemon.call(
                    "pull_object",
                    {"object_id": oid.binary(), "sources": live, "deadline_s": budget},
                    timeout=budget,
                )
                meta, failure = self._parse_pull_reply(reply)
                if meta is None and failure.get("deadline"):
                    # the transfer ran out of THIS caller's budget, with
                    # live sources: that is a timeout, not object loss —
                    # lineage reconstruction / relocation fallback would
                    # be wrong
                    raise GetTimeoutError(
                        f"fetch of {oid.hex()[:12]} ran out of budget "
                        f"mid-transfer ({failure.get('causes')})"
                    )
        if meta is None:
            # Stale locations can mean the holding node DRAINED and
            # replicated its copies away — consult the controller's
            # relocation directory before declaring the object lost
            # (lineage reconstruction re-runs the producing task; a
            # relocated copy costs one more pull).
            moved = await self._fetch_relocated(oid)
            if moved is not None:
                meta = moved
        if meta is None and skipped_dead_sources:
            # relocation directory had nothing and we never actually tried
            # the (dead-marked) sources: try them now rather than declare
            # loss on the strength of a push alone
            budget = effective_timeout(300.0)
            reply = await self.daemon.call(
                "pull_object",
                {
                    "object_id": oid.binary(),
                    "sources": [
                        (h, p) for (_nid, h, p) in locations if _nid != self.node_id
                    ],
                    "deadline_s": budget,
                },
                timeout=budget,
            )
            meta, failure = self._parse_pull_reply(reply)
            if meta is None and failure.get("deadline"):
                raise GetTimeoutError(
                    f"fetch of {oid.hex()[:12]} ran out of budget mid-transfer "
                    f"({failure.get('causes')})"
                )
        if meta is None:
            # ONE owner-side line for the whole fetch attempt: the
            # structured causes say which sources were missing the object
            # vs which transfers failed (the pull manager already logged
            # its own single summary daemon-side)
            causes = (failure or {}).get("causes", {})
            detail = (
                "no source holds the object"
                if (failure or {}).get("no_source")
                else "every transfer failed"
            )
            logger.warning(
                "fetch of %s from %d location(s) failed (%s): %s",
                oid.hex()[:12], len(locations), detail, causes,
            )
            raise ObjectLostError(
                oid, f"could not fetch from {locations} ({detail}: {causes})"
            )
        buf = self.shm.read(oid, meta["size"])
        value = serialization.deserialize_bytes(buf)
        if self.refcounter.owns(oid):
            self.refcounter.add_location(oid, self._self_location())
        return value

    async def _fetch_relocated(self, oid: ObjectID):
        """Drain-relocation fallback: ask the controller where a drained
        node replicated this object, pull from there. Returns local shm
        meta or None. Updates the owner's location set so later readers
        skip the detour."""
        from ray_tpu.core.deadline import effective_timeout

        try:
            loc = await self.controller.call(
                "get_relocated", {"object_id": oid.binary()}, timeout=10
            )
        except Exception:
            return None
        if loc is None:
            return None
        budget = effective_timeout(300.0)
        reply = await self.daemon.call(
            "pull_object",
            {
                "object_id": oid.binary(),
                "sources": [(loc["host"], loc["port"])],
                "deadline_s": budget,
            },
            timeout=budget,
        )
        meta, _failure = self._parse_pull_reply(reply)
        if meta is not None and self.refcounter.owns(oid):
            self.refcounter.add_location(
                oid, (loc["node_id"], loc["host"], loc["port"])
            )
        return meta

    # ------------------------------------------------------------------
    # wait — event-driven (reference ``raylet/wait_manager.h:25``): owned
    # refs complete via ownership-table callbacks (no RPC, no polling);
    # borrowed refs long-poll their owner's blocking get_object_status
    # once instead of one RPC per 5ms tick per ref.
    def wait(self, refs, num_returns, timeout, fetch_local):
        deadline = None if timeout is None else time.monotonic() + timeout

        async def _wait_all():
            done = [False] * len(refs)

            async def one(i: int, r: ObjectRef) -> None:
                await self._wait_ready(r, deadline)
                done[i] = True

            tasks = [asyncio.ensure_future(one(i, r)) for i, r in enumerate(refs)]
            try:
                # One immediate pass first: timeout=0 must still observe
                # refs that are already ready. Owned refs resolve without
                # suspending; borrowed refs need one status round-trip to
                # the owner, so grant them a short window — otherwise a
                # timeout=0 poll loop would NEVER see a ready borrowed ref.
                borrowed = any(
                    not self.refcounter.owns(r.id())
                    and not self.memory.contains(r.id())
                    and r.id().binary() not in self._borrowed_ready
                    for r in refs
                )
                expired = deadline is not None and time.monotonic() >= deadline
                if borrowed and expired:
                    # grant borrowed refs one status round-trip, but stop
                    # the moment num_returns is satisfied (an ALL_COMPLETED
                    # wait would burn the whole window even when an owned
                    # ref is already ready)
                    end = time.monotonic() + 0.2
                    while sum(done) < num_returns:
                        pend = [t for t in tasks if not t.done()]
                        left = end - time.monotonic()
                        if not pend or left <= 0:
                            break
                        await asyncio.wait(
                            pend,
                            timeout=left,
                            return_when=asyncio.FIRST_COMPLETED,
                        )
                else:
                    await asyncio.wait(tasks, timeout=0)
                while True:
                    if sum(done) >= num_returns:
                        break
                    pending = [t for t in tasks if not t.done()]
                    if not pending:
                        break
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        break
                    await asyncio.wait(
                        pending,
                        return_when=asyncio.FIRST_COMPLETED,
                        timeout=remaining,
                    )
            finally:
                for t in tasks:
                    if not t.done():
                        t.cancel()
            ready = [r for i, r in enumerate(refs) if done[i]]
            not_ready = [r for i, r in enumerate(refs) if not done[i]]
            return ready, not_ready

        ready, not_ready = self.io.run(_wait_all())
        if len(ready) > num_returns:
            not_ready = ready[num_returns:] + not_ready
            ready = ready[:num_returns]
        return ready, not_ready

    async def _wait_ready(self, ref: ObjectRef, deadline: Optional[float]) -> None:
        """Resolve when the ref is ready (or its owner is gone — get()
        surfaces that error)."""
        oid = ref.id()
        if self.memory.contains(oid):
            return
        if oid.binary() in self._borrowed_ready:
            return  # previously observed ready: readiness is monotone
        if self.refcounter.owns(oid):
            loop = asyncio.get_event_loop()
            ev = asyncio.Event()
            cb = _loop_event_setter(loop, ev)
            if self.refcounter.on_ready(oid, cb):
                return
            try:
                await ev.wait()
            finally:
                # timed-out/abandoned waiters must not leave closures
                # accumulating on the object
                self.refcounter.remove_ready_callback(oid, cb)
            return
        # borrowed: one blocking long-poll per step against the owner
        owner = self._owner_client(ref)
        while True:
            step = 30.0
            if deadline is not None:
                step = max(0.0, min(step, deadline - time.monotonic()))
            try:
                status = await owner.call(
                    "get_object_status",
                    {"object_id": oid.binary(), "timeout": step},
                    timeout=step + 10,
                )
            except Exception:
                return  # owner gone → get() will raise; count as "ready"
            if status["status"] in ("inline", "locations", "error", "unknown"):
                # unknown == freed at the owner: get() raises, count ready
                self._borrowed_ready[oid.binary()] = None
                while len(self._borrowed_ready) > 8192:
                    self._borrowed_ready.popitem(last=False)
                return
            if deadline is not None and time.monotonic() >= deadline:
                # caller's deadline: report not-ready by never resolving
                # (the outer asyncio.wait timeout cuts us off)
                await asyncio.sleep(3600)

    # ------------------------------------------------------------------
    # free / refcounting
    def _on_free(self, oid: ObjectID, obj) -> None:
        self.memory.delete(oid)
        created_here = self.shm.has_created(oid)
        recycle_pending = False
        for loc in obj.locations:
            _nid, host, port = loc
            if created_here and _nid == self.node_id:
                # our own segment: ask the daemon whether any reader ever
                # resolved it — if not, the inode goes to the reuse pool
                # (warm pages for the next put) instead of being unlinked
                recycle_pending = True
                self.io.post(self._delete_local_for_recycle(oid))
            else:
                self.io.post(self._delete_remote(host, port, oid))
        if not recycle_pending:
            # covers borrowed refs AND creator-side objects with no local
            # location (e.g. adoption failed): the mapping must not leak
            self.shm.release(oid)

    async def _delete_local_for_recycle(self, oid: ObjectID) -> None:
        try:
            recyclable = await self.daemon.call(
                "delete_object",
                {"object_id": oid.binary(), "allow_recycle": True},
                timeout=10,
            )
        except Exception:
            # Reply lost: the daemon may have granted recycling (entry
            # dropped, file NOT unlinked) — unlink defensively or the
            # segment leaks outside all accounting. The object is freed
            # either way, and a daemon-side _drop of a missing file is a
            # handled no-op.
            self.shm.release(oid)
            try:
                os.unlink("/dev/shm/" + _segment_name(oid))
            except OSError:
                pass
            return
        if recyclable is True:
            self.shm.recycle(oid)
        else:
            self.shm.release(oid)

    async def _delete_remote(self, host, port, oid, timeout: float = 10.0):
        # Bounded: the target node may be dead or partitioned (that's often
        # exactly why a delete is being sent) — never leave the coroutine
        # awaiting a reply forever.
        try:
            await self._client(host, port).call(
                "delete_object", {"object_id": oid.binary()}, timeout=timeout
            )
        except Exception:
            pass

    def free(self, object_ids: Sequence[ObjectID]) -> None:
        for oid in object_ids:
            if self.refcounter.owns(oid):
                self.refcounter.force_free(oid)
            else:
                self.memory.delete(oid)

    def release_hold(self, object_ids) -> None:
        for oid in object_ids:
            self.refcounter.remove_local(oid)

    def add_local_ref(self, ref: ObjectRef) -> None:
        if self.refcounter.owns(ref.id()):
            self.refcounter.add_local(ref.id())

    def remove_local_ref(self, ref: ObjectRef) -> None:
        if self._stopping:
            return
        if self.refcounter.owns(ref.id()):
            self.refcounter.remove_local(ref.id())
        elif ref.owner_address is not None:
            self.io.post(self._send_borrow(ref, "remove_borrower"))

    def register_borrow(self, ref: ObjectRef) -> None:
        if self.refcounter.owns(ref.id()):
            self.refcounter.add_local(ref.id())
        elif ref.owner_address is not None:
            self.io.post(self._send_borrow(ref, "add_borrower"))

    async def _send_borrow(self, ref: ObjectRef, method: str) -> None:
        try:
            await self._owner_client(ref).call(method, {"object_id": ref.binary()})
        except Exception:
            pass

    # ------------------------------------------------------------------
    # normal task submission (lease → push → results)
    def submit_task(self, spec: TaskSpec) -> None:
        for oid in spec.return_ids:
            self.refcounter.create_pending(oid, lineage=spec, hold=True)
        self._pin_deps(spec)
        # tracing: inherit the ambient context or sample a fresh root
        # (no-op + no allocation when unsampled); the stamp rides the
        # per-call wire fields so the executor re-enters it
        _tracing.stamp_spec(spec)
        spec._submit_ts = time.monotonic()  # stage-histogram anchor
        self.emit_task_event(spec, "SUBMITTED")
        self._buffer_submit(False, spec)

    def _buffer_submit(self, is_actor: bool, spec: TaskSpec) -> None:
        with self._submit_lock:
            self._submit_buf.append((is_actor, spec))
            schedule = not self._submit_scheduled
            if schedule:
                self._submit_scheduled = True
        if schedule:
            self.io.loop.call_soon_threadsafe(self._drain_submits)

    def _drain_submits(self) -> None:
        """Runs on the io loop: dispatch every buffered spec. While a
        producer thread is mid-burst, the drain RE-ARMS itself with a
        plain call_soon and keeps ``_submit_scheduled`` set — submits
        landing during the burst skip the cross-thread self-pipe wakeup
        (a ~1ms syscall under load on virtualized kernels), paying it
        once per burst instead of once per task."""
        with self._submit_lock:
            batch, self._submit_buf = self._submit_buf, []
        for is_actor, spec in batch:
            try:
                if is_actor:
                    self._enqueue_actor_task(spec)
                else:
                    self._enqueue_normal(spec)
            except Exception as e:  # noqa: BLE001 — never strand returns
                logger.exception("enqueue failed for %s", spec.name)
                self._fail_returns(
                    spec, e if isinstance(e, RayTpuError) else RayTpuError(repr(e))
                )
        with self._submit_lock:
            if self._submit_buf:
                self.io.loop.call_soon(self._drain_submits)
            else:
                self._submit_scheduled = False

    def _try_recover(self, oid: ObjectID, observed_locations=None) -> bool:
        """Lineage reconstruction (``object_recovery_manager.h:90``): if
        every copy of an owned object is lost, resubmit the producing
        TaskSpec. Recursive losses recover naturally — the re-executed
        task's workers fetch its args through the same get paths, which
        recover *their* losses via this owner. Returns True if a
        reconstruction is running (or already was); the caller re-waits."""
        if not GLOBAL_CONFIG.lineage_pinning_enabled:
            return False
        state, spec, stale = self.refcounter.begin_reconstruction(
            oid,
            GLOBAL_CONFIG.max_lineage_reconstructions,
            observed_locations=observed_locations,
        )
        if state == "pending":
            return True
        if state != "started":
            return False
        logger.info(
            "reconstructing lost object %s by resubmitting task %s",
            oid.hex()[:12],
            spec.name,
        )
        # Best-effort delete of previously-tracked copies: a transiently
        # unreachable node may still hold one, which would otherwise leak
        # (and, for a nondeterministic task, diverge from the new value).
        for ret_id, locations in stale.items():
            for loc in locations:
                _nid, host, port = loc
                self.io.post(self._delete_remote(host, port, ret_id))
        self._pin_deps(spec)
        self.io.loop.call_soon_threadsafe(self._enqueue_normal, spec)
        return True

    def _pin_deps(self, spec: TaskSpec) -> None:
        for ref in spec.dependencies():
            if self.refcounter.owns(ref.id()):
                self.refcounter.add_submitted(ref.id())

    def _unpin_deps(self, spec: TaskSpec) -> None:
        for ref in spec.dependencies():
            if self.refcounter.owns(ref.id()):
                self.refcounter.remove_submitted(ref.id())

    # Lease reuse (reference lease pipelining,
    # ``transport/normal_task_submitter.cc:351``): tasks queue per
    # *scheduling class* (resources + strategy); each class runs up to
    # max_lease_pumps pump coroutines, and a pump holds ONE worker lease,
    # pushing queued task after queued task onto it — the request/return
    # lease round-trips amortize across the whole queue instead of being
    # paid per task.
    def _sched_class_key(self, spec: TaskSpec):
        return (
            tuple(sorted(spec.resources.items())),
            repr(spec.scheduling_strategy),
        )

    def _enqueue_normal(self, spec: TaskSpec) -> None:
        """Queue a normal task for lease-reuse submission. Must run on the
        io loop (touches the class-queue/pump state)."""
        key = self._sched_class_key(spec)
        q = self._class_queues.get(key)
        if q is None:
            q = self._class_queues[key] = _ClassQueue()
        q.specs.append(spec)
        q.work.set()
        self._retries_left[spec.task_id.binary()] = spec.max_retries
        # One pump to start; growth is demand-driven (see _drain_on_lease):
        # eager fan-out costs more than it buys for micro-tasks (lease
        # churn + worker wakeups), while slow tasks trigger sibling pumps
        # within lease_pump_growth_s anyway.
        if q.pumps == 0:
            q.pumps = 1
            if len(self._pump_tasks) > 64:
                self._pump_tasks = [t for t in self._pump_tasks if not t.done()]
            self._pump_tasks.append(
                asyncio.ensure_future(self._pump_class(key, q, spec))
            )

    async def _pump_class(self, key, q: "_ClassQueue", template: TaskSpec) -> None:
        try:
            while q.specs:
                # the lease is acquired on behalf of the request at the
                # queue HEAD — attribute its span there, not to the spec
                # that happened to start this pump (which may be long
                # finished, or unsampled while the head is sampled)
                head_trace = q.specs[0].trace_ctx if q.specs else None
                lease_t0 = time.monotonic()
                lease_t0_us = _timeline._now_us() if head_trace else 0.0
                try:
                    grant = await self._acquire_lease(template)
                    self._observe_stage("lease", time.monotonic() - lease_t0)
                    if head_trace is not None:
                        _tracing.record_span(
                            head_trace, "lease", lease_t0_us,
                            _timeline._now_us(), category="task",
                        )
                except RayTpuError as e:
                    # class-wide failure (infeasible / lease timeout):
                    # fail everything currently queued for this class
                    while q.specs:
                        s = q.specs.popleft()
                        self._finalize_spec(s, error=e)
                    return
                try:
                    await self._drain_on_lease(key, q, grant)
                finally:
                    try:
                        await self._client(
                            grant["daemon_host"], grant["daemon_port"], role="noded"
                        ).call("return_lease", {"lease_id": grant["lease_id"]})
                    except Exception:
                        pass
        except Exception:  # noqa: BLE001 — never leave returns pending
            logger.exception("class pump failed")
            while q.specs:
                s = q.specs.popleft()
                self._finalize_spec(s, error=RayTpuError("submission pump failed"))
        finally:
            q.pumps -= 1
            if q.pumps == 0 and not q.specs:
                self._class_queues.pop(key, None)

    def _maybe_grow_pumps(self, key, q: "_ClassQueue") -> None:
        """A push has been in flight past the growth threshold with work
        still queued: the tasks are long (or blocked) enough that another
        lease is worth its churn — spawn a sibling pump."""
        if q.specs and 0 < q.pumps < GLOBAL_CONFIG.max_lease_pumps:
            q.pumps += 1
            self._pump_tasks.append(
                asyncio.ensure_future(self._pump_class(key, q, q.specs[0]))
            )

    async def _drain_on_lease(self, key, q: "_ClassQueue", grant: Dict[str, Any]) -> None:
        """Push queued specs onto one held lease until the queue runs dry
        (with a short linger for stragglers) or the worker dies."""
        worker_client = self._client(grant["host"], grant["port"], role="worker")
        loop = asyncio.get_event_loop()
        while True:
            if not q.specs:
                # Linger: hold the lease briefly for follow-on work, but
                # wake IMMEDIATELY when something is enqueued (a plain
                # sleep would add up to linger_s of latency per task on
                # serial submit-get-submit callers).
                q.work.clear()
                try:
                    await asyncio.wait_for(
                        q.work.wait(), GLOBAL_CONFIG.lease_linger_s
                    )
                except (asyncio.TimeoutError, TimeoutError):
                    pass
                if not q.specs:
                    return
            # Pop a small batch: one RPC carries several specs (executed
            # serially worker-side), amortizing framing + syscalls.
            # ADAPTIVE size: batch only when the queue floods faster than
            # the pumps drain — with few tasks per pump the batch is 1,
            # preserving cross-worker parallelism for long tasks (and
            # keeping force-cancel's worker kill from taking batchmates
            # down with it).
            limit = max(
                1,
                min(
                    GLOBAL_CONFIG.lease_push_batch,
                    (len(q.specs) + 1) // max(1, q.pumps),
                ),
            )
            batch: List[TaskSpec] = []
            while q.specs and len(batch) < limit:
                spec = q.specs[0]
                # Batch-dependency guard: a spec whose owned dep is still
                # PENDING must not ride behind its producer in ONE batch —
                # the worker executes the batch serially and the producer's
                # result only reaches this owner in the batched reply, so
                # the dependent would deadlock waiting for it. Close the
                # batch instead; the next push happens after this reply is
                # processed. (Taken alone it may still block the lane on a
                # dep produced elsewhere — that's latency, not deadlock.)
                if batch and self._has_pending_owned_dep(spec):
                    break
                q.specs.popleft()
                tid = spec.task_id.binary()
                if tid in self._cancelled_tasks:
                    self._finalize_spec(
                        spec, error=TaskCancelledError(spec.task_id.hex()[:16])
                    )
                    continue
                submit_ts = getattr(spec, "_submit_ts", None)
                if submit_ts is not None:
                    # queue stage: submit → popped by a lease pump
                    queued_s = time.monotonic() - submit_ts
                    self._observe_stage("queue", queued_s)
                    if spec.trace_ctx is not None:
                        now_us = _timeline._now_us()
                        _tracing.record_span(
                            spec.trace_ctx, f"queue::{spec.name}",
                            now_us - queued_s * 1e6, now_us, category="task",
                        )
                batch.append(spec)
            if not batch:
                continue
            for spec in batch:
                self._inflight_workers[spec.task_id.binary()] = (
                    grant["host"],
                    grant["port"],
                )
            grow_handle = loop.call_later(
                GLOBAL_CONFIG.lease_pump_growth_s, self._maybe_grow_pumps, key, q
            )
            push_t0 = time.monotonic()
            traced = next((s for s in batch if s.trace_ctx is not None), None)
            push_t0_us = _timeline._now_us() if traced is not None else 0.0
            try:
                reply = await worker_client.call(
                    "push_batch",
                    {"specs": [encode_spec(s) for s in batch]},
                    timeout=None,
                    connect_timeout=3.0,
                )
            except ChaosInjectedError:
                # injected BEFORE the handler ran: re-push on the same
                # (healthy) lease without consuming task retries
                for spec in reversed(batch):
                    q.specs.appendleft(spec)
                await asyncio.sleep(0.02)
                continue
            except ConnectionLost:
                for spec in batch:
                    tid = spec.task_id.binary()
                    if tid in self._cancelled_tasks:
                        # force-cancel kills the worker: that drop IS the
                        # cancellation, not a crash to retry
                        self._finalize_spec(
                            spec, error=TaskCancelledError(spec.task_id.hex()[:16])
                        )
                    elif self._retries_left.get(tid, 0) > 0:
                        self._retries_left[tid] -= 1
                        logger.info("task %s worker died; retrying", spec.name)
                        q.specs.appendleft(spec)
                    else:
                        self._finalize_spec(
                            spec,
                            error=WorkerCrashedError(
                                f"worker died executing {spec.name}"
                            ),
                        )
                return  # lease is dead
            except Exception as e:  # noqa: BLE001
                # Non-transport failure (e.g. worker-side packaging error
                # surfaced as RemoteError): the batch's returns must never
                # be left PENDING forever.
                logger.exception("push_batch failed")
                for spec in batch:
                    self._finalize_spec(
                        spec,
                        error=e if isinstance(e, RayTpuError) else RayTpuError(repr(e)),
                    )
                return
            finally:
                grow_handle.cancel()
                for spec in batch:
                    self._inflight_workers.pop(spec.task_id.binary(), None)
            # push stage: the whole batch's RPC round trip (execution
            # included); one span per batch — per-spec copies of the
            # same interval would only add noise to the trace
            self._observe_stage("push", time.monotonic() - push_t0)
            if traced is not None:
                _tracing.record_span(
                    traced.trace_ctx, f"push_batch::{len(batch)}",
                    push_t0_us, _timeline._now_us(), category="task",
                )
            replies = reply["replies"]
            for i, spec in enumerate(batch):
                if i >= len(replies):
                    # defensive: a short reply list must not strand the
                    # tail's returns in PENDING forever
                    self._finalize_spec(
                        spec, error=RayTpuError("push_batch reply truncated")
                    )
                    continue
                tid = spec.task_id.binary()
                try:
                    retry = self._process_reply(
                        spec, replies[i], self._retries_left.get(tid, 0)
                    )
                except Exception as e:  # noqa: BLE001
                    logger.exception("reply processing failed for %s", spec.name)
                    self._finalize_spec(spec, error=RayTpuError(repr(e)))
                    continue
                if retry:
                    self._retries_left[tid] -= 1
                    q.specs.appendleft(spec)
                else:
                    self._finalize_spec(spec)

    def _has_pending_owned_dep(self, spec: TaskSpec) -> bool:
        for ref in spec.dependencies():
            obj = self.refcounter.get(ref.id())
            if obj is not None and not obj.ready():
                return True
        return False

    @staticmethod
    def _observe_stage(stage: str, seconds: float) -> None:
        from ray_tpu.observability.rpc_metrics import TASK_STAGE_SECONDS

        TASK_STAGE_SECONDS.observe(seconds, labels={"stage": stage})

    def _finalize_spec(self, spec: TaskSpec, error: Optional[Exception] = None) -> None:
        """A spec leaves the submission system: record failure (if any),
        release dep pins and cancellation/retry bookkeeping."""
        if error is not None:
            self._fail_returns(spec, error)
        tid = spec.task_id.binary()
        self._cancelled_tasks.pop(tid, None)
        self._retries_left.pop(tid, None)
        self._unpin_deps(spec)
        submit_ts = getattr(spec, "_submit_ts", None)
        if submit_ts is not None:
            self._observe_stage("total", time.monotonic() - submit_ts)
        if spec.trace_ctx is not None and error is None:
            # result-push landed at the owner: instant completion marker
            now_us = _timeline._now_us()
            _tracing.record_span(
                spec.trace_ctx, f"complete::{spec.name}", now_us, now_us,
                category="task",
            )
        self.emit_task_event(spec, "FAILED" if error is not None else "FINISHED")

    # ------------------------------------------------------------------
    # streaming generators (owner side)
    def create_stream(self, spec: TaskSpec):
        from ray_tpu.core.streaming import ObjectRefStream

        stream = ObjectRefStream(spec.task_id.binary())
        with self._streams_lock:
            self._streams[spec.task_id.binary()] = stream
        return stream

    def stream_next(self, task_id: bytes, index: int, timeout: Optional[float]):
        from ray_tpu.core.streaming import _END

        with self._streams_lock:
            stream = self._streams.get(task_id)
        if stream is None:
            raise RayTpuError("unknown stream (task already cleaned up?)")
        out = stream.next_blocking(index, timeout)
        if out is _END:
            # last consumer position reached: drop the stream record
            with self._streams_lock:
                self._streams.pop(task_id, None)
        else:
            self._report_stream_consumed(task_id, stream, index)
        return out

    def _report_stream_consumed(self, task_id: bytes, stream, index: int) -> None:
        """Throttled consumer-position report to the producing worker —
        what resumes a generator paused on backpressure."""
        threshold = GLOBAL_CONFIG.streaming_generator_backpressure_items
        if threshold <= 0:
            return
        step = max(1, threshold // 2)
        last = getattr(stream, "_last_reported", 0)
        if index - last < step:
            return
        stream._last_reported = index
        target = self._inflight_workers.get(task_id)
        if target is None:
            return
        host, port = target

        async def _send():
            try:
                await self._client(host, port, role="worker").call(
                    "stream_consumed",
                    {"task_id": task_id, "consumed": index},
                    timeout=10,
                )
            except Exception:
                pass  # producer done/dead: nothing to unblock

        self.io.post(_send())

    def abandon_stream(self, task_id: bytes, consumed_pos: int) -> None:
        """Generator dropped before exhaustion: release holds on items the
        consumer never took and cancel the producer (no point computing a
        stream nobody reads). Holds the streams lock so an item push
        racing the abandonment can't create a hold nobody releases."""
        with self._streams_lock:
            stream = self._streams.pop(task_id, None)
            if stream is None:
                return
            with stream._cond:
                undelivered = list(stream._items.values())
                # gate the cancel on PRODUCER COMPLETION, not item-1
                # readiness: a finished stream (total set / errored) has
                # nothing running to cancel, while an unfinished one must
                # be cancelled even if its first item was consumed long ago
                finished = stream._total is not None or stream._error is not None
        self.release_hold(undelivered)
        if not finished:
            self._cancel_task_by_id(task_id, force=False)

    def _on_stream_item(self, msg: Dict[str, Any]) -> None:
        """Worker-pushed stream item: record the value + ref."""
        task_id = msg["task_id"]
        oid = ObjectID(msg["object_id"])
        with self._streams_lock:
            stream = self._streams.get(task_id)
            if stream is None:
                # stream abandoned: a late shm item would otherwise sit in
                # the producing node's store forever — best-effort delete
                if msg["kind"] == "shm":
                    _nid, host, port = msg["location"]
                    self.io.post(self._delete_remote(host, port, oid))
                return
            # entry holds until the generator hands out the real
            # ObjectRef; created under the lock so abandon_stream either
            # sees this item (and releases it) or this push sees the
            # stream already gone
            self.refcounter.create_pending(oid, hold=True)
            stream.append(msg["index"], oid)
        if msg["kind"] == "inline":
            self.memory.put(oid, msg["data"])
            self.refcounter.mark_available_inline(oid, msg["data"])
        else:
            self.refcounter.mark_available_at(oid, tuple(msg["location"]))

    def _finalize_stream(self, spec: TaskSpec, error: Optional[Exception]) -> None:
        stream = self._streams.get(spec.task_id.binary())
        if stream is None:
            return
        if error is not None:
            stream.fail(error)

    # ------------------------------------------------------------------
    # task events (batched → controller; reference task_event_buffer)
    def emit_task_event(self, spec: TaskSpec, state: str) -> None:
        if not GLOBAL_CONFIG.task_events_enabled:
            return
        ev = {
            "task_id": spec.task_id.binary(),
            "name": spec.name,
            "state": state,
            "ts": time.time(),
        }
        with self._task_events_lock:
            self._task_events.append(ev)
            schedule = not self._task_events_flushing
            if schedule:
                self._task_events_flushing = True
        if schedule:
            self.io.post(self._flush_task_events())

    async def _flush_task_events(self) -> None:
        try:
            await asyncio.sleep(0.2)  # batch window
            with self._task_events_lock:
                events, self._task_events = self._task_events, []
            if events:
                await self.controller.call(
                    "task_events", {"events": events}, timeout=10
                )
        except Exception:
            pass  # observability is best-effort
        finally:
            # events that arrived while the RPC was in flight must not
            # strand in the buffer until the next emit — reschedule
            with self._task_events_lock:
                again = bool(self._task_events) and not self._stopping
                if not again:
                    self._task_events_flushing = False
            if again:
                self.io.post(self._flush_task_events())

    async def _acquire_lease(self, spec: TaskSpec) -> Dict[str, Any]:
        """Lease with spillback-following (reference lease protocol).

        Placement-group leases go straight to a daemon holding one of the
        PG's bundles (only those daemons have the bundle pools)."""
        from ray_tpu.core.task_spec import PlacementGroupScheduling

        daemon = self.daemon
        daemon_addr = self.daemon_addr
        if isinstance(spec.scheduling_strategy, PlacementGroupScheduling):
            target = await self._pg_lease_target(spec.scheduling_strategy)
            if target is not None:
                daemon_addr = target
                daemon = self._client(*target, role="noded")
        deadline = time.monotonic() + GLOBAL_CONFIG.worker_lease_timeout_s * 10
        infeasible_since: Optional[float] = None
        while True:
            try:
                reply = await daemon.call(
                    "request_lease",
                    {"resources": spec.resources, "strategy": spec.scheduling_strategy},
                    timeout=60,
                    connect_timeout=3.0,
                )
            except (ConnectionLost, asyncio.TimeoutError):
                if daemon is self.daemon:
                    raise RayTpuError("local node daemon unreachable")
                # spillback target died — fall back to the local daemon
                daemon, daemon_addr = self.daemon, self.daemon_addr
                await asyncio.sleep(0.1)
                continue
            if "grant" in reply:
                g = reply["grant"]
                g["daemon_host"], g["daemon_port"] = daemon_addr
                return g
            if "spillback" in reply:
                host, port = reply["spillback"]
                daemon = self._client(host, port, role="noded")
                daemon_addr = (host, port)
                continue
            if reply.get("infeasible"):
                # infeasible is terminal only after the patience window:
                # on an autoscaled cluster the demand this request parks
                # is what LAUNCHES the node that makes it feasible
                now = time.monotonic()
                if infeasible_since is None:
                    infeasible_since = now
                if now - infeasible_since >= GLOBAL_CONFIG.infeasible_fail_after_s:
                    raise RayTpuError(
                        f"task {spec.name} requires {spec.resources} which no node can satisfy"
                    )
                await asyncio.sleep(0.5)
                continue
            infeasible_since = None
            await asyncio.sleep(reply.get("retry_after", 0.05))
            if isinstance(spec.scheduling_strategy, PlacementGroupScheduling):
                target = await self._pg_lease_target(spec.scheduling_strategy)
                if target is not None:
                    daemon_addr = target
                    daemon = self._client(*target, role="noded")
            else:
                # fall back to local daemon (cluster may have changed)
                daemon = self.daemon
                daemon_addr = self.daemon_addr
            if time.monotonic() > deadline:
                raise RayTpuError(f"lease for {spec.name} timed out")

    async def _pg_lease_target(self, strategy) -> Optional[Tuple[str, int]]:
        """Daemon address of a node holding one of the PG's bundles."""
        info = await self.controller.call("get_pg", {"pg_id": strategy.pg_id})
        if not info or not info.get("nodes"):
            return None
        node_ids = info["nodes"]
        indices = info.get("bundle_indices", list(range(len(node_ids))))
        wanted = None
        if strategy.bundle_index >= 0:
            for nid, idx in zip(node_ids, indices):
                if idx == strategy.bundle_index:
                    wanted = nid
                    break
        else:
            wanted = node_ids[0]
        if wanted is None:
            return None
        for n in await self.controller.call("nodes"):
            if n["node_id"] == wanted and n["Alive"]:
                return (n["host"], n["port"])
        return None

    def _process_reply(self, spec: TaskSpec, reply: Dict[str, Any], retries_left: int) -> bool:
        """Record results with the ownership table. Returns True if the
        task should be retried (app-level error + retry_exceptions)."""
        results: List[Tuple[bytes, str, Any]] = reply["results"]
        # Check for retryable application errors first.
        for _oid, kind, payload in results:
            if kind == "error":
                err = pickle.loads(payload)
                if isinstance(err, TaskError) and self._should_retry_app_error(spec, err, retries_left):
                    return True
        for oid_bytes, kind, payload in results:
            if kind == "stream_end":
                stream = self._streams.get(spec.task_id.binary())
                if stream is not None:
                    stream.complete(payload)  # payload = total item count
                continue
            if kind == "error" and spec.num_returns == "streaming":
                # streams have no fixed return ids — fail the stream itself
                self._finalize_stream(spec, pickle.loads(payload))
                continue
            oid = ObjectID(oid_bytes)
            if kind == "inline":
                self.memory.put(oid, payload)
                self.refcounter.mark_available_inline(oid, payload)
            elif kind == "shm":
                self.refcounter.mark_available_at(oid, tuple(payload))
            elif kind == "error":
                self.refcounter.mark_failed(oid, pickle.loads(payload))
        return False

    def _should_retry_app_error(self, spec: TaskSpec, err: TaskError, retries_left: int) -> bool:
        if retries_left <= 0 or not spec.retry_exceptions:
            return False
        if spec.retry_exceptions is True:
            return True
        try:
            return isinstance(err.cause, tuple(spec.retry_exceptions))
        except TypeError:
            return False

    def _fail_returns(self, spec: TaskSpec, error: Exception) -> None:
        for oid in spec.return_ids:
            self.refcounter.mark_failed(oid, error)
        if spec.num_returns == "streaming":
            self._finalize_stream(spec, error)

    # ------------------------------------------------------------------
    # actors
    def create_actor(self, spec: TaskSpec) -> None:
        _tracing.stamp_spec(spec)
        with self._actors_lock:
            st = self._actors.setdefault(spec.actor_id, _ActorState())
            st.max_task_retries = spec.max_task_retries
            st.max_concurrency = max(1, spec.max_concurrency)
            # Pin the creation spec for the actor's (restartable)
            # lifetime: its args may be implicit-put objects (e.g. a list
            # containing ObjectRefs) whose ONLY owner-side reference is
            # the ObjectRef held by this spec — dropping it before the
            # (possibly restarted) creation task fetches args would free
            # them under the actor.
            st.creation_spec = spec
        self.io.run(self.controller.call("register_actor", {"spec": spec}))

    def _stale_controller_push(self, msg: Dict[str, Any]) -> bool:
        """Worker half of controller epoch fencing: state pushes carry
        the sender's incarnation epoch (controller._publish). Track the
        highest seen; drop anything lower — it was emitted by a deposed
        controller racing its own takeover, and applying it would roll
        actor/node/PG state back behind the new incumbent's."""
        epoch = msg.get("controller_epoch", 0)
        if not epoch:
            return False  # ephemeral (no-persistence) controller
        if epoch < self._controller_epoch_seen:
            logger.warning(
                "dropping stale controller push (epoch %d < %d)",
                epoch, self._controller_epoch_seen,
            )
            return True
        self._controller_epoch_seen = epoch
        return False

    def _on_actor_push(self, msg: Dict[str, Any]) -> None:
        if self._stale_controller_push(msg):
            return
        actor_id = msg["actor_id"]
        with self._actors_lock:
            st = self._actors.setdefault(actor_id, _ActorState())
            st.state = msg["state"]
            if msg.get("address") is not None:
                st.address = msg["address"]
            if msg.get("reason"):
                st.reason = msg["reason"]
            if msg["state"] == "DEAD":
                st.creation_spec = None  # release pinned creation args
            st.event.set()

    def _on_node_push(self, msg: Dict[str, Any]) -> None:
        """Controller-pushed node membership/state changes. Libraries
        (Train's drain watch, Serve) register listeners to react to
        DRAINING the moment the warning lands, not on a poll interval."""
        if self._stale_controller_push(msg):
            return
        nid = msg.get("node_id")
        if nid is not None:
            if msg.get("alive"):
                self._dead_nodes.discard(nid)
            elif msg.get("state") == "DEAD" or msg.get("alive") is False:
                self._dead_nodes.add(nid)
        for cb in list(self._node_event_listeners):
            try:
                cb(msg)
            except Exception:
                logger.debug("node event listener failed", exc_info=True)

    def add_node_event_listener(self, cb) -> None:
        """``cb(msg)`` with msg = {node_id, alive, state?, reason?}; runs
        on the io loop thread — keep it non-blocking."""
        self._node_event_listeners.append(cb)

    def remove_node_event_listener(self, cb) -> None:
        try:
            self._node_event_listeners.remove(cb)
        except ValueError:
            pass

    def _on_log_push(self, msg: Dict[str, Any]) -> None:
        import sys

        node = msg["node_id"].hex()[:8]
        for entry in msg.get("batch", []):
            worker = entry["worker"].replace("worker-", "").replace(".log", "")
            for line in entry["lines"]:
                print(f"({worker}, node={node}) {line}", file=sys.stderr)

    def _on_pg_push(self, msg: Dict[str, Any]) -> None:
        # Only track PGs this process has expressed interest in (created or
        # waited on): pushes are cluster-wide, so caching every one would
        # grow without bound in long-lived workers under PG churn. Waiters
        # that miss a push recover via the poll fallback in wait_pg_ready.
        if self._stale_controller_push(msg):
            return
        ev = self._pg_events.get(msg["pg_id"])
        if ev is None:
            return
        self._pg_states[msg["pg_id"]] = msg["state"]
        ev.set()

    async def _resolve_actor(self, actor_id: ActorID) -> _ActorState:
        with self._actors_lock:
            st = self._actors.setdefault(actor_id, _ActorState())
        deadline = time.monotonic() + 120
        loop = asyncio.get_event_loop()
        while time.monotonic() < deadline:
            if st.state == "ALIVE" and st.address is not None:
                return st
            if st.state == "DEAD":
                return st
            info = await self.controller.call("get_actor_info", {"actor_id": actor_id})
            if info is not None:
                with self._actors_lock:
                    st.state = info["state"]
                    st.address = info["address"]
                    st.reason = info.get("reason", "")
                    st.max_concurrency = info.get("max_concurrency", st.max_concurrency)
                    st.max_task_retries = info.get("max_task_retries", st.max_task_retries)
                if st.state in ("ALIVE", "DEAD") and (st.state == "DEAD" or st.address):
                    return st
            await asyncio.sleep(0.05)
        raise RayTpuError(f"actor {actor_id.hex()[:8]} did not become ready")

    def submit_actor_task(self, spec: TaskSpec) -> None:
        for oid in spec.return_ids:
            self.refcounter.create_pending(oid, hold=True)
        self._pin_deps(spec)
        _tracing.stamp_spec(spec)
        spec._submit_ts = time.monotonic()
        self._buffer_submit(True, spec)

    def _enqueue_actor_task(self, spec: TaskSpec) -> None:
        """Per-actor ordered dispatch (``SequentialActorSubmitQueue``):
        calls to a max_concurrency==1 actor are pushed strictly in
        submission order; concurrent/async actors dispatch directly.
        Must run on the io loop."""
        with self._actors_lock:
            st = self._actors.setdefault(spec.actor_id, _ActorState())
            # handle-carried hint: a borrower's first dispatch must not
            # serialize a concurrent actor through the ordered pump
            if spec.max_concurrency > st.max_concurrency:
                st.max_concurrency = spec.max_concurrency
        if st.max_concurrency > 1:
            asyncio.ensure_future(self._submit_actor(spec))
            return
        q = self._actor_queues.get(spec.actor_id)
        if q is None:
            q = self._actor_queues[spec.actor_id] = asyncio.Queue()
            self._pump_tasks.append(asyncio.ensure_future(self._actor_pump(spec.actor_id, q)))
        q.put_nowait(spec)

    async def _actor_pump(self, actor_id: ActorID, q: "asyncio.Queue") -> None:
        # Batched ordered pushes: pop everything queued and send ONE
        # framed RPC (the worker executes the batch serially, seq-ordered)
        # — the round-trip amortizes across the burst exactly like the
        # normal-task lease pipelining, while strict submission order is
        # preserved even across worker restarts (the whole batch retries
        # in order).
        carry: Optional[TaskSpec] = None
        while not self._stopping:
            spec = carry if carry is not None else await q.get()
            carry = None
            batch = [spec]
            limit = GLOBAL_CONFIG.lease_push_batch
            while len(batch) < limit and not q.empty():
                nxt = q.get_nowait()
                # same batch-dependency guard as the normal-task path: a
                # call whose owned dep is pending (possibly produced by a
                # batchmate) must start the NEXT batch
                if self._has_pending_owned_dep(nxt):
                    carry = nxt
                    break
                batch.append(nxt)
            try:
                await self._submit_actor_batch(batch)
            except Exception as e:  # noqa: BLE001 — the pump must survive
                logger.exception("actor batch submission failed")
                for s in batch:
                    self._fail_returns(
                        s, e if isinstance(e, RayTpuError) else RayTpuError(repr(e))
                    )

    async def _submit_actor(self, spec: TaskSpec) -> None:
        try:
            await self._submit_actor_inner(spec)
        except Exception as e:  # noqa: BLE001 — never leave returns pending
            logger.exception("actor task %s submission failed", spec.name)
            self._fail_returns(spec, e if isinstance(e, RayTpuError) else RayTpuError(repr(e)))

    async def _recover_push_target(self, actor_id, st, binding) -> bool:
        """Shared ConnectionLost recovery for actor pushes (ordered-batch
        AND direct submit paths): consult the controller, refresh the
        cached actor state, and decide whether the SAME live incarnation
        can be re-pushed under the bound request id (True — the re-push
        is dedup-protected, consumes no task-retry budget, and is safe
        even for streaming calls) or the binding must be invalidated so
        the caller applies its per-spec retry/fail semantics (False).

        The controller consult is deliberately NOT guarded: if the
        control plane is also gone there is nothing to wait for — the
        exception propagates to the caller's catch, which fails the
        pending returns (a guarded retry here would loop forever on the
        cached ALIVE state)."""
        info = await self.controller.call("get_actor_info", {"actor_id": actor_id})
        with self._actors_lock:
            if info is not None:
                st.state = info["state"]
                st.address = info["address"]
                st.reason = info.get("reason", "")
            else:
                st.state = "DEAD"
        if (
            st.state == "ALIVE"
            and st.address is not None
            and (st.address.host, st.address.port)
            == (binding.client.host, binding.client.port)
            and binding.can_retry_same_target()
        ):
            # same live incarnation, connection blip only: this is what
            # makes non-idempotent serve calls safely auto-retryable
            # while the replica is reachable (serve/router.py contract)
            binding.note_retry()
            await asyncio.sleep(0.1)
            return True
        # actor moved/died (or retries exhausted): the next push is a
        # DIFFERENT logical request — fresh id
        binding.invalidate()
        return False

    async def _submit_actor_batch(self, batch: List[TaskSpec]) -> None:
        """Push an ordered batch of calls to one actor; retries keep order
        (the whole remaining batch is re-pushed after a restart)."""
        from ray_tpu.core.transport_retry import PushBinding

        actor_id = batch[0].actor_id
        all_specs = list(batch)
        with self._actors_lock:
            st = self._actors.setdefault(actor_id, _ActorState())
        retries_left = {s.task_id.binary(): st.max_task_retries for s in batch}
        # Request-id reuse (exactly-once): every re-push of THIS batch to
        # the SAME replica/client shares one dedup slot, so a push whose
        # reply was lost after execution is answered from the server's
        # reply cache instead of running twice. A new client (actor moved)
        # or a trimmed batch gets a fresh id — different logical request.
        binding = PushBinding()
        try:
            while batch:
                try:
                    st = await self._resolve_actor(actor_id)
                except Exception as e:  # noqa: BLE001
                    for s in batch:
                        self._fail_returns(s, RayTpuError(repr(e)))
                    return
                if st.state == "DEAD":
                    for s in batch:
                        self._fail_returns(
                            s, ActorDiedError(actor_id, st.reason or "actor is dead")
                        )
                    return
                client = self._client(st.address.host, st.address.port, role="worker")
                push_rid = binding.bind(client)
                for s in batch:
                    # streaming methods need the producer's address for
                    # consumer-position (backpressure) reports
                    if s.num_returns == "streaming":
                        self._inflight_workers[s.task_id.binary()] = (
                            st.address.host,
                            st.address.port,
                        )
                try:
                    reply = await client.call(
                        "push_batch",
                        {"specs": [encode_spec(s) for s in batch]},
                        timeout=None,
                        connect_timeout=3.0,
                        request_id=push_rid,
                    )
                except ChaosInjectedError:
                    # injected fault: retry the batch under the SAME
                    # request id — if the handler already ran (reply
                    # dropped), the dedup cache answers; no task retry
                    # budget is consumed either way
                    await asyncio.sleep(0.02)
                    continue
                except ConnectionLost:
                    if await self._recover_push_target(actor_id, st, binding):
                        continue
                    survivors: List[TaskSpec] = []
                    for s in batch:
                        tid = s.task_id.binary()
                        # a partially-consumed stream must not replay
                        if (
                            st.state == "DEAD"
                            or retries_left[tid] <= 0
                            or s.num_returns == "streaming"
                        ):
                            self._fail_returns(
                                s,
                                ActorDiedError(
                                    actor_id, st.reason or "actor worker died mid-call"
                                ),
                            )
                        else:
                            retries_left[tid] -= 1
                            survivors.append(s)
                    batch = survivors
                    if batch:
                        await asyncio.sleep(0.1)
                    continue
                except Exception as e:  # noqa: BLE001
                    for s in batch:
                        self._fail_returns(
                            s, e if isinstance(e, RayTpuError) else RayTpuError(repr(e))
                        )
                    return
                replies = reply["replies"]
                for i, s in enumerate(batch):
                    if i >= len(replies):
                        self._fail_returns(s, RayTpuError("push_batch reply truncated"))
                        continue
                    try:
                        self._process_reply(s, replies[i], 0)
                    except Exception as e:  # noqa: BLE001
                        logger.exception("reply processing failed for %s", s.name)
                        self._fail_returns(s, RayTpuError(repr(e)))
                return
        finally:
            for s in all_specs:
                self._unpin_deps(s)
                self._inflight_workers.pop(s.task_id.binary(), None)

    async def _submit_actor_inner(self, spec: TaskSpec) -> None:
        from ray_tpu.core.transport_retry import PushBinding

        try:
            with self._actors_lock:
                st = self._actors.setdefault(spec.actor_id, _ActorState())
            retries_left = st.max_task_retries
            # request-id reuse across re-pushes to the same incarnation
            # (see _submit_actor_batch for the exactly-once rationale)
            binding = PushBinding()
            while True:
                st = await self._resolve_actor(spec.actor_id)
                if st.state == "DEAD":
                    self._fail_returns(
                        spec, ActorDiedError(spec.actor_id, st.reason or "actor is dead")
                    )
                    return
                client = self._client(st.address.host, st.address.port, role="worker")
                push_rid = binding.bind(client)
                if spec.num_returns == "streaming":
                    self._inflight_workers[spec.task_id.binary()] = (
                        st.address.host,
                        st.address.port,
                    )
                try:
                    reply = await client.call(
                        "push_task",
                        {"spec": encode_spec(spec)},
                        timeout=None,
                        connect_timeout=3.0,
                        request_id=push_rid,
                    )
                except ChaosInjectedError:
                    await asyncio.sleep(0.02)
                    continue
                except ConnectionLost:
                    if await self._recover_push_target(spec.actor_id, st, binding):
                        continue
                    if (
                        st.state == "DEAD"
                        or retries_left <= 0
                        or spec.num_returns == "streaming"
                    ):
                        self._fail_returns(
                            spec,
                            ActorDiedError(
                                spec.actor_id,
                                st.reason or "actor worker died mid-call",
                            ),
                        )
                        return
                    retries_left -= 1
                    await asyncio.sleep(0.1)
                    continue
                self._process_reply(spec, reply, 0)
                return
        finally:
            self._unpin_deps(spec)
            self._inflight_workers.pop(spec.task_id.binary(), None)

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        self.io.run(
            self.controller.call("kill_actor", {"actor_id": actor_id, "no_restart": no_restart})
        )

    def kill_actor_nowait(self, actor_id: ActorID) -> None:
        async def _kill():
            try:
                await self.controller.call(
                    "kill_actor", {"actor_id": actor_id, "no_restart": True}
                )
            except Exception:
                pass

        if not self._stopping:
            self.io.post(_kill())

    def mark_actor_no_restart(self, actor_id: ActorID) -> None:
        async def _mark():
            try:
                await self.controller.call(
                    "kill_actor",
                    {"actor_id": actor_id, "no_restart": True, "drain": True},
                )
            except Exception:
                pass

        if not self._stopping:
            self.io.post(_mark())

    def cancel(self, ref: ObjectRef, force: bool, recursive: bool) -> None:
        """Cancel the task producing ``ref`` (``CoreWorker::CancelTask``).

        Queued tasks are failed with TaskCancelledError at the next
        submission checkpoint; a running task gets the error raised in
        its execution thread (cooperative — blocking C calls won't see
        it); ``force=True`` kills the executing worker process. Actor
        tasks are not cancellable (reference parity for sync actors)."""
        oid = ref.id()
        task_id = oid.task_id()
        if oid.is_put():
            raise ValueError("cannot cancel(): ref came from put(), not a task")
        if not self.refcounter.owns(oid):
            # Borrowed ref: submission state lives at the owner — forward
            # (reference CancelTask routes through the owner).
            owner = self._owner_client(ref)

            async def _forward():
                try:
                    await owner.call(
                        "cancel_owned_task",
                        {"object_id": oid.binary(), "force": force},
                        timeout=10,
                    )
                except Exception:
                    pass  # owner gone → task is moot anyway

            self.io.post(_forward())
            return
        self._cancel_owned(oid, force)

    def _cancel_owned(self, oid: ObjectID, force: bool) -> None:
        obj = self.refcounter.get(oid)
        if obj is not None and obj.ready():
            return  # already finished — nothing to cancel (reference no-op)
        self._cancel_task_by_id(oid.task_id().binary(), force)

    def _cancel_task_by_id(self, tid: bytes, force: bool) -> None:
        """Mark a task cancelled and notify its executing worker (shared
        by ref-cancel and stream-abandon paths)."""
        self._cancelled_tasks[tid] = None
        while len(self._cancelled_tasks) > 8192:
            self._cancelled_tasks.popitem(last=False)
        target = self._inflight_workers.get(tid)
        if target is not None:
            host, port = target

            async def _send():
                try:
                    await self._client(host, port, role="worker").call(
                        "cancel_task", {"task_id": tid, "force": force}, timeout=10
                    )
                except Exception:
                    pass  # worker already gone

            self.io.post(_send())

    def get_named_actor(self, name: str, namespace: str):
        info = self.io.run(
            self.controller.call("get_named_actor", {"name": name, "namespace": namespace})
        )
        if info is None:
            return None
        return (
            info["actor_id"],
            info["method_opts"],
            info["owner"],
            info.get("max_concurrency", 1),
        )

    def list_named_actors(self, all_namespaces: bool):
        return self.io.run(
            self.controller.call("list_named_actors", {"all_namespaces": all_namespaces})
        )

    # ------------------------------------------------------------------
    # placement groups (client side)
    def create_pg(self, pg_id: bytes, bundles, strategy: str, name: str = "") -> None:
        self._pg_events.setdefault(pg_id, threading.Event())
        self.io.run(
            self.controller.call(
                "create_pg",
                {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name},
            )
        )

    _PG_TERMINAL = ("CREATED", "INFEASIBLE", "REMOVED")

    _PG_POLL_INTERVAL_S = 2.0

    def wait_pg_ready(self, pg_id: bytes, timeout: Optional[float]) -> str:
        """Block until the PG reaches a terminal state.

        Push-driven with a polling fallback: interest (the event) is
        registered before the first poll, so any transition after that poll
        is pushed; slow re-polls only cover dropped pushes. The polled value
        is never written to the push cache — a stale in-flight PENDING reply
        must not clobber a concurrently-pushed terminal state.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        ev = self._pg_events.setdefault(pg_id, threading.Event())
        next_poll = 0.0
        state: Optional[str] = None
        while True:
            pushed = self._pg_states.get(pg_id)
            if pushed in self._PG_TERMINAL:
                state = pushed
            elif time.monotonic() >= next_poll:
                info = self.io.run(self.controller.call("get_pg", {"pg_id": pg_id}))
                # create_pg registers synchronously, so an id the controller
                # doesn't know was removed (the table drops entries on
                # removal to bound memory).
                state = info["state"] if info else "REMOVED"
                next_poll = time.monotonic() + self._PG_POLL_INTERVAL_S
            if state in self._PG_TERMINAL:
                # Reclaim wait state here too: only a *local* remove_pg
                # cleans up otherwise, and this process may not be the
                # remover.
                self._pg_states.pop(pg_id, None)
                self._pg_events.pop(pg_id, None)
                return state
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return state or "PENDING"
            # Clear → recheck → wait: clearing first avoids hot-spinning on
            # an event set by an earlier push, and the recheck catches a
            # push that landed before the clear (e.g. while the poll RPC
            # above was in flight) so its wakeup is never lost.
            ev.clear()
            pushed = self._pg_states.get(pg_id)
            if pushed in self._PG_TERMINAL:
                self._pg_states.pop(pg_id, None)
                self._pg_events.pop(pg_id, None)
                return pushed
            ev.wait(min(0.2, remaining) if remaining is not None else 0.2)

    def remove_pg(self, pg_id: bytes) -> None:
        self.io.run(self.controller.call("remove_pg", {"pg_id": pg_id}))
        # Drop per-pg wait state so long-lived drivers cycling many PGs
        # (e.g. the microbenchmark) don't grow these maps without bound.
        self._pg_states.pop(pg_id, None)
        self._pg_events.pop(pg_id, None)

    def get_pg(self, pg_id: bytes):
        return self.io.run(self.controller.call("get_pg", {"pg_id": pg_id}))

    def get_named_pg(self, name: str):
        return self.io.run(self.controller.call("get_named_pg", {"name": name}))

    def pg_table(self):
        return self.io.run(self.controller.call("pg_table"))

    # ------------------------------------------------------------------
    # kv / cluster info
    def kv_put(self, key: bytes, value: bytes) -> None:
        self.io.run(self.controller.call("kv_put", {"key": key, "value": value}))

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self.io.run(self.controller.call("kv_get", {"key": key}))

    def kv_keys(self, prefix: bytes = b"") -> List[bytes]:
        return self.io.run(self.controller.call("kv_keys", {"prefix": prefix}))

    def kv_del(self, key: bytes) -> None:
        self.io.run(self.controller.call("kv_del", {"key": key}))

    # ------------------------------------------------------------------
    # timeline export: worker-side chunks land in the controller's
    # BOUNDED export table (byte budget + node-death reap) instead of
    # growing the generic KV forever (observability/timeline.py)
    def export_timeline_chunk(self, key: str, blob: bytes) -> None:
        try:
            self.io.run(
                self.controller.call(
                    "export_events",
                    {"key": key, "blob": blob, "node_id": self.node_id},
                    timeout=10,
                )
            )
        except Exception:
            pass  # observability export is best-effort

    def collect_timeline_chunks(self) -> List[bytes]:
        try:
            return self.io.run(
                self.controller.call("collect_events", {}, timeout=30)
            )
        except Exception:
            return []

    def cluster_status(self) -> Dict[str, Any]:
        """Live cluster state in one call (the `ray list` equivalent):
        nodes / actors / task summary / per-node object stats / PGs /
        jobs, served from the controller's bounded tables."""
        return self.io.run(self.controller.call("cluster_status", {}, timeout=30))

    def cluster_resources(self) -> Dict[str, float]:
        return self.io.run(self.controller.call("cluster_resources"))

    def available_resources(self) -> Dict[str, float]:
        return self.io.run(self.controller.call("available_resources"))

    def nodes(self) -> List[Dict[str, Any]]:
        return self.io.run(self.controller.call("nodes"))

    def drain_node(self, node_id: bytes, reason: str = "drain requested") -> bool:
        """Operator-initiated graceful drain (reference ``DrainNode``)."""
        reply = self.io.run(
            self.controller.call(
                "drain_node", {"node_id": node_id, "reason": reason}, timeout=30
            )
        )
        return bool(reply and reply.get("ok"))

    # ------------------------------------------------------------------
    # owner services (every process with a CoreWorker serves these)
    async def w_get_object_status(self, payload, conn):
        oid = ObjectID(payload["object_id"])
        timeout = payload.get("timeout", 30.0)
        if not self.refcounter.owns(oid):
            data = self.memory.get(oid)
            if data is not None:
                return {"status": "inline", "data": data}
            return {"status": "unknown"}
        # Event-driven long-poll: park on the io loop, NOT an executor
        # thread — dozens of borrowers long-polling must not saturate the
        # owner's thread pool (reference pubsub serves these from buffers).
        obj = self.refcounter.get(oid)
        if timeout != 0 and (obj is None or not obj.ready()):
            loop = asyncio.get_event_loop()
            ev = asyncio.Event()
            cb = _loop_event_setter(loop, ev)
            if not self.refcounter.on_ready(oid, cb):
                try:
                    await asyncio.wait_for(ev.wait(), timeout)
                except (asyncio.TimeoutError, TimeoutError):
                    pass
                finally:
                    self.refcounter.remove_ready_callback(oid, cb)
            obj = self.refcounter.get(oid)
        if obj is None:
            return {"status": "unknown"}
        if obj.state == ObjState.FAILED:
            return {"status": "error", "error": pickle.dumps(obj.error)}
        if obj.state != ObjState.AVAILABLE:
            return {"status": "pending"}
        if obj.inline is not None:
            return {"status": "inline", "data": obj.inline}
        return {"status": "locations", "locations": list(obj.locations)}

    async def w_stream_consumed(self, payload, conn):
        """Owner's consumer-position report for a streaming generator
        running on this worker (backpressure resume signal)."""
        if self.executor is not None:
            self.executor.update_stream_consumed(
                payload["task_id"], payload["consumed"]
            )
        return True

    async def w_cancel_task(self, payload, conn):
        """Cancel an executing/queued task on this worker."""
        if self.executor is None:
            return False
        return self.executor.cancel_task(payload["task_id"], payload.get("force", False))

    async def w_cancel_owned_task(self, payload, conn):
        """Borrower-forwarded cancel: this process owns the target ref."""
        self._cancel_owned(ObjectID(payload["object_id"]), payload.get("force", False))
        return True

    async def w_recover_object(self, payload, conn):
        """Borrower-initiated lineage reconstruction: a borrower failed to
        fetch any copy; the owner resubmits the producing task."""
        return self._try_recover(
            ObjectID(payload["object_id"]),
            observed_locations=payload.get("observed"),
        )

    async def w_add_borrower(self, payload, conn):
        self.refcounter.add_borrower(ObjectID(payload["object_id"]))
        return True

    async def w_remove_borrower(self, payload, conn):
        self.refcounter.remove_borrower(ObjectID(payload["object_id"]))
        return True

    async def w_delete_object(self, payload, conn):
        self.memory.delete(ObjectID(payload["object_id"]))
        return True

    async def w_ping(self, payload, conn):
        return "pong"

    async def w_set_accelerator_env(self, payload, conn):
        """Daemon-assigned device isolation for pooled workers (dedicated
        actor workers get it via spawn env). Effective as long as the
        accelerator runtime hasn't initialized in this process yet."""
        from ray_tpu.accelerators import get_accelerator_manager

        mgr = get_accelerator_manager(payload["resource"])
        if mgr is not None:
            ids = payload.get("ids")
            if ids:
                # undo ONLY the daemon's chip-less CPU pin from spawn time
                # (jax has not initialized yet — the daemon grants the
                # lease only after this reply): restore the pre-pin value
                # rather than clobbering an operator-set JAX_PLATFORMS
                prepin = os.environ.pop("RAY_TPU_PREPIN_JAX_PLATFORMS", None)
                if prepin is not None:
                    if prepin:
                        os.environ["JAX_PLATFORMS"] = prepin
                    else:
                        os.environ.pop("JAX_PLATFORMS", None)
                mgr.set_current_process_visible_accelerator_ids([str(i) for i in ids])
        return True

    # execution services are registered when an executor is attached
    async def _decode_spec(self, entry) -> TaskSpec:
        """Rebuild a full TaskSpec from its wire form: template-spliced
        entries are ``("t", template_id, per-call)``; the invariant
        prefix is fetched from the control-plane KV once per template."""
        if isinstance(entry, TaskSpec):
            return entry
        _tag, tid, pc = entry
        tmpl = self._tmpl_cache.get(tid)
        if tmpl is None:
            from ray_tpu.core.function_manager import (
                TEMPLATE_KV_PREFIX,
                template_from_payload,
            )

            payload = await self.controller.call(
                "kv_get",
                {"key": TEMPLATE_KV_PREFIX + tid},
                timeout=30,
                retries=GLOBAL_CONFIG.rpc_max_retries,
            )
            if payload is None:
                raise RayTpuError(f"unknown task template {tid.hex()}")
            tmpl = template_from_payload(tid, payload)
            self._tmpl_cache[tid] = tmpl
        return tmpl.from_percall(pc)

    async def w_push_batch(self, payload, conn):
        """Batched task push on a held lease: specs execute serially,
        one framed reply (lease-pipelining companion). Per-spec isolation:
        one task's packaging failure becomes ITS error reply — it must
        not discard batchmates' already-computed results by failing the
        whole RPC."""
        if self.executor is None:
            raise RuntimeError("this process does not execute tasks")
        # Per-spec decode isolation: an undecodable entry (template
        # missing from the KV) becomes ITS error reply — return ids are
        # recoverable from the per-call tuple without the template.
        specs: List[Any] = []
        decode_errors: Dict[int, Dict[str, Any]] = {}
        for idx, entry in enumerate(payload["specs"]):
            try:
                specs.append(await self._decode_spec(entry))
            except Exception as e:  # noqa: BLE001 — isolate batchmates
                logger.exception("spec decode failed in batch")
                err = TaskError("decode", e)
                ret_ids = entry[2][3] if not isinstance(entry, TaskSpec) else [
                    oid.binary() for oid in entry.return_ids
                ]
                decode_errors[idx] = {
                    "results": [(rid, "error", pickle.dumps(err)) for rid in ret_ids]
                }
                specs.append(None)
        live = [s for s in specs if s is not None]
        if decode_errors and (
            not live or any(s.kind == TaskKind.ACTOR_TASK for s in live)
        ):
            # Per-spec isolation is only safe for all-NORMAL batches: an
            # ordered actor's failed spec would leave a sequence-number
            # hole (its seq never advances) and wedge every batchmate in
            # _wait_turn. Fail the whole RPC instead — the owner's batch
            # error path fails all returns, no hang. (An all-failed
            # batch can't prove it wasn't an actor batch: same verdict.)
            raise RayTpuError("task template decode failed in actor batch")
        if not decode_errors:
            fast = self.executor.handle_push_batch_fast(live, conn=conn)
            if fast is not None:
                return {"replies": await fast}
        replies = []
        for idx, spec in enumerate(specs):
            if spec is None:
                replies.append(decode_errors[idx])
                continue
            try:
                replies.append(await self.executor.handle_push_task(spec, conn=conn))
            except Exception as e:  # noqa: BLE001
                logger.exception("task %s failed in batch", spec.name)
                err = TaskError(spec.name, e)
                if spec.num_returns == "streaming":
                    results = [(b"", "error", pickle.dumps(err))]
                else:
                    results = [
                        (oid.binary(), "error", pickle.dumps(err))
                        for oid in spec.return_ids
                    ]
                replies.append({"results": results})
        return {"replies": replies}

    async def w_push_task(self, payload, conn):
        if self.executor is None:
            raise RuntimeError("this process does not execute tasks")
        spec = await self._decode_spec(payload["spec"])
        return await self.executor.handle_push_task(spec, conn=conn)

    async def w_run_actor_creation(self, payload, conn):
        if self.executor is None:
            raise RuntimeError("this process does not execute tasks")
        return await self.executor.handle_actor_creation(payload["spec"])

    async def w_exit(self, payload, conn):
        import os

        self.io.loop.call_later(0.05, os._exit, 0)
        return True

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._stopping = True

        async def _teardown():
            for t in self._pump_tasks:
                t.cancel()
            for c in self._clients.values():
                await c.close()
            await self.controller.close()
            await self.daemon.close()
            await self.server.stop()

        try:
            self.io.run(_teardown(), timeout=5)
        except Exception:
            pass
        self.shm.close_all()
        self.io.stop()
