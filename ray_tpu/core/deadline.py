"""Deadline propagation: one time budget shared by nested blocking calls.

The hang class this kills: caller passes ``timeout=120`` to an outer
call, the implementation stacks *independent* inner timeouts (a 60s get
inside a retry loop inside a 30s RPC…) and the outer budget quietly
becomes minutes — or, with an inner ``timeout=None``, forever. Instead a
:class:`Deadline` is entered once at the outer boundary and every nested
``get()``/``wait()`` (and any code that asks :func:`effective_timeout`)
inherits the *remaining* budget.

Propagation is two-layer:

* in-process: a ``contextvars.ContextVar`` — async tasks and the sync
  call stack both see the ambient deadline (``deadline_scope``).
* cross-process: task submission stamps the remaining budget onto the
  ``TaskSpec`` (``deadline_remaining_s``); the executing worker re-enters
  a scope with that budget, so a ``get()`` *inside* a remote task is
  truncated by the driver's deadline too (reference analogue: gRPC
  deadline propagation, which the reference leans on implicitly).

Absolute wall/monotonic timestamps never cross process boundaries —
only remaining seconds, re-anchored on arrival (clocks differ; in-flight
time is eroded from the budget by construction on the worker side only
after the spec lands, which is the same slack gRPC accepts).
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Iterator, Optional


class Deadline:
    """An absolute monotonic deadline with remaining-budget arithmetic."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = at

    @classmethod
    def after(cls, timeout_s: Optional[float]) -> Optional["Deadline"]:
        if timeout_s is None:
            return None
        return cls(time.monotonic() + max(0.0, timeout_s))

    def remaining(self) -> float:
        return self.at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


_current_deadline: contextvars.ContextVar[Optional[Deadline]] = contextvars.ContextVar(
    "ray_tpu_deadline", default=None
)


def current_deadline() -> Optional[Deadline]:
    return _current_deadline.get()


def remaining() -> Optional[float]:
    """Seconds left in the ambient deadline, or None if none is set."""
    d = _current_deadline.get()
    return None if d is None else d.remaining()


def effective_timeout(timeout_s: Optional[float]) -> Optional[float]:
    """Clamp an explicit timeout by the ambient deadline: the tighter of
    the two wins; ``None`` defers entirely to the ambient budget (and
    stays None when there is none). An exhausted budget returns 0.0 —
    callers' timeout machinery turns that into an immediate timeout
    instead of a hang."""
    d = _current_deadline.get()
    if d is None:
        return timeout_s
    left = max(0.0, d.remaining())
    if timeout_s is None:
        return left
    return min(timeout_s, left)


@contextmanager
def deadline_scope(timeout_s: Optional[float]) -> Iterator[Optional[Deadline]]:
    """Enter a deadline of ``timeout_s`` seconds (no-op for None). Nested
    scopes never EXTEND the ambient budget — the effective deadline is
    the tighter of the new and inherited ones, so an inner
    ``deadline_scope(300)`` cannot escape an outer 10s budget."""
    new = Deadline.after(timeout_s)
    inherited = _current_deadline.get()
    if new is None or (inherited is not None and inherited.at <= new.at):
        new = inherited
    token = _current_deadline.set(new)
    try:
        yield new
    finally:
        _current_deadline.reset(token)
