"""Public exception types (cf. reference ``python/ray/exceptions.py``)."""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised; re-raised at ``get`` with the remote traceback.

    Reference: ``RayTaskError`` — the error object is stored in place of
    the task's return value and surfaces on every dependent get.
    """

    def __init__(self, function_name: str, cause: BaseException, tb: Optional[str] = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(function_name, cause)

    def __str__(self) -> str:
        return (
            f"task {self.function_name} failed with "
            f"{type(self.cause).__name__}: {self.cause}\n"
            f"remote traceback:\n{self.remote_traceback}"
        )


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died (cf. ``WorkerCrashedError``)."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead and will not be restarted (cf. ``RayActorError``)."""

    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object's value was lost and could not be reconstructed."""

    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        super().__init__(f"object {object_id} lost: {reason}")


class ObjectFreedError(RayTpuError):
    """Object was explicitly freed by its owner."""


class OwnerDiedError(ObjectLostError):
    """The worker that owned this object died (cf. ``OwnerDiedError``)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` exceeded its timeout (cf. ``GetTimeoutError``)."""


class TaskCancelledError(RayTpuError):
    """Task was cancelled (cf. ``TaskCancelledError``)."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} was cancelled")


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's max_pending_calls was exceeded."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment failed to build."""


class NodeDiedError(RayTpuError):
    """The node hosting the operation died."""
