"""Task execution helpers shared by the local backend and cluster workers.

Server side of the task hot path (reference: Cython
``task_execution_handler`` ``_raylet.pyx:2239`` feeding the user function,
wrapping exceptions, and fanning results out to the store).
"""

from __future__ import annotations

import inspect
import logging
from typing import Any, Callable, List, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.exceptions import TaskError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.refs import ObjectRef
from ray_tpu.core.task_spec import TaskSpec

logger = logging.getLogger(__name__)


def resolve_args(spec: TaskSpec, get_ref: Callable[[ObjectRef], Any]) -> Tuple[list, dict]:
    """Materialize positional/keyword args: refs via `get_ref`, inline
    values via deserialization (they were serialized at submit)."""
    args = []
    for tag, payload in spec.args:
        if tag == "ref":
            args.append(get_ref(payload))
        else:
            args.append(serialization.deserialize_bytes(payload))
    kwargs = {}
    for tag, key, payload in spec.kwargs:
        if tag == "ref":
            kwargs[key] = get_ref(payload)
        else:
            kwargs[key] = serialization.deserialize_bytes(payload)
    return args, kwargs


def unpack_returns(spec: TaskSpec, result: Any) -> List[Tuple[ObjectID, Any]]:
    """Split a function result across the task's return object ids."""
    n = spec.num_returns
    if n == 0:
        return []
    if n == 1:
        return [(spec.return_ids[0], result)]
    if isinstance(n, int):
        try:
            values = list(result)
        except TypeError:
            raise ValueError(
                f"task {spec.name} declared num_returns={n} but returned "
                f"non-iterable {type(result).__name__}"
            )
        if len(values) != n:
            raise ValueError(
                f"task {spec.name} declared num_returns={n} but returned "
                f"{len(values)} values"
            )
        return list(zip(spec.return_ids, values))
    raise NotImplementedError(f"num_returns={n!r}")


def run_function(spec: TaskSpec, fn: Callable, args: list, kwargs: dict) -> List[Tuple[ObjectID, Any]]:
    """Invoke `fn`; on user exception return TaskError placeholders for every
    return id (stored in place of values, surfacing at get — reference
    behavior)."""
    try:
        result = fn(*args, **kwargs)
        if inspect.iscoroutine(result):
            import asyncio

            # Runs on an execution lane thread (no ambient event loop):
            # drive the coroutine on a private loop.
            loop = asyncio.new_event_loop()
            try:
                result = loop.run_until_complete(result)
            finally:
                loop.close()
        return unpack_returns(spec, result)
    except Exception as e:  # noqa: BLE001 - user code boundary
        err = TaskError(spec.name, e)
        ids = spec.return_ids if spec.num_returns != 0 else []
        return [(oid, err) for oid in ids]
