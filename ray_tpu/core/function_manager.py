"""Function/class export and import.

Equivalent of the reference's function manager
(``python/ray/_private/function_manager.py``): the driver pickles each
remote function/class once, stores it in the control plane's KV under a
content hash, and ships only the hash inside task specs; workers import and
cache on first use. In local mode the "KV" is a process-local dict.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Any, Callable, Dict, Optional

import cloudpickle

FUNCTION_KV_PREFIX = b"fn:"
TEMPLATE_KV_PREFIX = b"tmpl:"


class TemplateTable:
    """Client-side registry of cached task-spec templates (the invariant
    spec prefix, see ``task_spec.SpecTemplate``). Same shape as the
    function table: pickle once, store in the control-plane KV under a
    content hash, ship only the 16-byte id per call; executors fetch and
    cache on first use."""

    def __init__(self, kv_put: Callable[[bytes, bytes], None]):
        self._kv_put = kv_put
        self._registered: set = set()
        self._lock = threading.Lock()

    def register(self, fields: Dict[str, Any]) -> "Any":
        """``fields``: SpecTemplate constructor kwargs sans template_id.
        Returns the SpecTemplate (registered in the KV exactly once)."""
        import pickle

        from ray_tpu.core.task_spec import SpecTemplate

        payload = pickle.dumps(fields, protocol=5)
        template_id = hashlib.sha256(payload).digest()[:16]
        with self._lock:
            known = template_id in self._registered
        if not known:
            # mark registered only AFTER the put lands: a concurrent
            # registrant of the same hash must not skip the put and
            # submit against a template the KV doesn't hold yet (the
            # duplicate put is idempotent — same key, same bytes)
            self._kv_put(TEMPLATE_KV_PREFIX + template_id, payload)
            with self._lock:
                self._registered.add(template_id)
        return SpecTemplate(template_id=template_id, **fields)


def template_from_payload(template_id: bytes, payload: bytes):
    """Executor-side: rebuild a SpecTemplate from its KV payload."""
    import pickle

    from ray_tpu.core.task_spec import SpecTemplate

    return SpecTemplate(template_id=template_id, **pickle.loads(payload))


class FunctionTable:
    """Client-side view of the exported-function table."""

    def __init__(self, kv_put: Callable[[bytes, bytes], None], kv_get: Callable[[bytes], Optional[bytes]]):
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._exported: Dict[bytes, bytes] = {}
        self._cache: Dict[bytes, Any] = {}
        # identity → function_id: export() sits on the per-submit hot path,
        # so the cloudpickle+sha256 of an already-exported callable must be
        # skipped. Weak keys: a redefined function is a different object
        # (gets its own export), and dropped functions don't pin entries.
        self._by_identity: "weakref.WeakKeyDictionary[Any, bytes]" = (
            weakref.WeakKeyDictionary()
        )
        self._lock = threading.Lock()

    def export(self, obj: Any) -> bytes:
        """Pickle `obj` (function or class), store under its hash, return id."""
        from ray_tpu.core.serialization import ensure_importable_or_by_value

        try:
            hit = self._by_identity.get(obj)
        except TypeError:  # unhashable / non-weakrefable callable
            hit = None
        if hit is not None:
            return hit
        ensure_importable_or_by_value(obj)
        payload = cloudpickle.dumps(obj)
        function_id = hashlib.sha256(payload).digest()[:16]
        with self._lock:
            if function_id in self._exported:
                try:
                    self._by_identity[obj] = function_id
                except TypeError:
                    pass
                return function_id
            self._exported[function_id] = payload
            self._cache[function_id] = obj
            try:
                self._by_identity[obj] = function_id
            except TypeError:
                pass
        try:
            self._kv_put(FUNCTION_KV_PREFIX + function_id, payload)
        except BaseException:
            # roll back: a cached id whose KV write never landed would
            # short-circuit every future export of this object while
            # remote loads fail forever with "function not exported"
            with self._lock:
                self._exported.pop(function_id, None)
                self._cache.pop(function_id, None)
                try:
                    del self._by_identity[obj]
                except (KeyError, TypeError):
                    pass
            raise
        return function_id

    def load(self, function_id: bytes) -> Any:
        with self._lock:
            hit = self._cache.get(function_id)
        if hit is not None:
            return hit
        payload = self._kv_get(FUNCTION_KV_PREFIX + function_id)
        if payload is None:
            raise KeyError(f"function {function_id.hex()} not exported")
        obj = cloudpickle.loads(payload)
        with self._lock:
            self._cache[function_id] = obj
        return obj
