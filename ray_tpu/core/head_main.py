"""Head process: controller + head-node daemon in one process.

Reference topology: head node runs ``gcs_server`` + ``raylet``
(``_private/node.py:1354``); here both live on one asyncio loop in one
process. Prints a single JSON line with the ports so the spawning driver
can connect.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys


async def amain(args) -> None:
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.controller import Controller
    from ray_tpu.core.node_daemon import NodeDaemon

    if args.system_config:
        GLOBAL_CONFIG.apply_system_config(json.loads(args.system_config))
    persist = None
    if args.session_dir:
        os.makedirs(args.session_dir, exist_ok=True)
        persist = os.path.join(args.session_dir, "controller_snapshot.pkl")
    controller = Controller(persist_path=persist)
    cport = await controller.start()
    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = args.num_cpus
    daemon = NodeDaemon(
        "127.0.0.1",
        cport,
        resources=resources or None,
        session_dir=args.session_dir,
    )
    dport = await daemon.start()
    print(json.dumps({"controller_port": cport, "daemon_port": dport}), flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    # driver-owned clusters die with their driver (hang defense: a
    # SIGKILLed pytest/bench must not orphan head_main forever); the
    # detached CLI path never sets the env var, so it survives
    from ray_tpu.util.reaper import start_orphan_watch

    start_orphan_watch(lambda: loop.call_soon_threadsafe(stop.set))
    await stop.wait()
    await daemon.stop()
    await controller.stop()


def main() -> None:
    import faulthandler

    faulthandler.enable()
    faulthandler.register(signal.SIGUSR2, all_threads=True)
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--resources", type=str, default="")
    parser.add_argument("--session-dir", type=str, default=None)
    parser.add_argument("--system-config", type=str, default="")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
