"""Binary identifiers with embedded lineage.

Design (cf. reference ``src/ray/common/id.h``): every id is a fixed-width
byte string; larger ids embed smaller ones so ownership and lineage can be
recovered from the id alone:

    JobID (4B)  ⊂  ActorID (12B = 8B unique + JobID)
    ActorID     ⊂  TaskID  (20B = 8B unique + ActorID)
    TaskID      ⊂  ObjectID (24B = TaskID + 4B little-endian return index)

``ObjectID.for_put`` uses index 0 with a synthetic "put" task id; task
returns use index >= 1 (reference: ``ObjectID::FromIndex``). Ids are
immutable, hashable, msgpack-friendly (raw bytes), and render as hex.
"""

from __future__ import annotations

import os
import threading

_JOB_UNIQUE = 4
_ACTOR_UNIQUE = 8
_TASK_UNIQUE = 8


class _EntropyPool:
    """Buffered ``os.urandom``: id minting sits on the task-submit hot
    path, and the per-call getrandom syscall costs up to ~1ms under load
    on virtualized kernels (measured on the bench box — it was 60% of
    submit time). One 4 KiB draw amortizes the syscall over ~250 task
    ids. Fork-safe: the child drops the inherited buffer so parent and
    child can never mint the same bytes."""

    _REFILL = 4096

    def __init__(self):
        self._buf = b""
        self._off = 0
        self._lock = threading.Lock()
        if hasattr(os, "register_at_fork"):
            os.register_at_fork(after_in_child=self._reset)

    def _reset(self) -> None:
        # fork hook: fresh lock too — the parent may have forked while a
        # thread held it, and an inherited locked lock has no owner to
        # release it in the child
        self._lock = threading.Lock()
        self._buf = b""
        self._off = 0

    def take(self, n: int) -> bytes:
        with self._lock:
            if self._off + n > len(self._buf):
                self._buf = os.urandom(self._REFILL)
                self._off = 0
            out = self._buf[self._off : self._off + n]
            self._off += n
            return out


_entropy = _EntropyPool()


def random_bytes(n: int) -> bytes:
    """Pooled randomness for id generation (not for secrets)."""
    return _entropy.take(n)

JOB_ID_SIZE = _JOB_UNIQUE
ACTOR_ID_SIZE = _ACTOR_UNIQUE + JOB_ID_SIZE  # 12
TASK_ID_SIZE = _TASK_UNIQUE + ACTOR_ID_SIZE  # 20
OBJECT_ID_SIZE = TASK_ID_SIZE + 4  # 24
NODE_ID_SIZE = 16
WORKER_ID_SIZE = 16
PLACEMENT_GROUP_ID_SIZE = 12


class BaseID:
    """Immutable fixed-width binary id."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, (bytes, bytearray)):
            raise TypeError(f"{type(self).__name__} expects bytes, got {type(binary)}")
        binary = bytes(binary)
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} expects {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(random_bytes(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._bytes == other._bytes

    def __hash__(self) -> int:
        # cached: ids key every hot-path dict (ownership table, memory
        # store, retry maps), so the tuple hash showed up in profiles
        try:
            return self._hash
        except AttributeError:
            self._hash = h = hash((type(self).__name__, self._bytes))
            return h

    def __lt__(self, other) -> bool:
        return self._bytes < other._bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = JOB_ID_SIZE
    __slots__ = ()

    _counter_lock = threading.Lock()
    _counter = 0

    @classmethod
    def from_index(cls, index: int) -> "JobID":
        return cls(index.to_bytes(cls.SIZE, "little"))


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE
    __slots__ = ()


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE
    __slots__ = ()


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE
    __slots__ = ()


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(random_bytes(_ACTOR_UNIQUE) + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        """The actor id used for non-actor tasks of a job."""
        return cls(b"\x00" * _ACTOR_UNIQUE + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[_ACTOR_UNIQUE:])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE
    __slots__ = ()

    @classmethod
    def for_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(random_bytes(_TASK_UNIQUE) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        """The synthetic task id of a driver (owns driver-created objects)."""
        return cls(b"\xff" * _TASK_UNIQUE + ActorID.nil_for_job(job_id).binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[_TASK_UNIQUE:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE
    __slots__ = ()

    MAX_INDEX = 2**32 - 1

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        """The i-th return of `task_id` (index >= 1; 0 reserved for puts)."""
        if not 0 <= index <= cls.MAX_INDEX:
            raise ValueError(f"object index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts share the task id namespace; flip the high bit of the index
        # so put ids never collide with return ids.
        return cls(task_id.binary() + (0x80000000 | put_index).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_SIZE:], "little")

    def is_put(self) -> bool:
        return bool(self.index() & 0x80000000)

    def job_id(self) -> JobID:
        return self.task_id().job_id()
