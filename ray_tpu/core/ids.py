"""Binary identifiers with embedded lineage.

Design (cf. reference ``src/ray/common/id.h``): every id is a fixed-width
byte string; larger ids embed smaller ones so ownership and lineage can be
recovered from the id alone:

    JobID (4B)  ⊂  ActorID (12B = 8B unique + JobID)
    ActorID     ⊂  TaskID  (20B = 8B unique + ActorID)
    TaskID      ⊂  ObjectID (24B = TaskID + 4B little-endian return index)

``ObjectID.for_put`` uses index 0 with a synthetic "put" task id; task
returns use index >= 1 (reference: ``ObjectID::FromIndex``). Ids are
immutable, hashable, msgpack-friendly (raw bytes), and render as hex.
"""

from __future__ import annotations

import os
import threading

_JOB_UNIQUE = 4
_ACTOR_UNIQUE = 8
_TASK_UNIQUE = 8

JOB_ID_SIZE = _JOB_UNIQUE
ACTOR_ID_SIZE = _ACTOR_UNIQUE + JOB_ID_SIZE  # 12
TASK_ID_SIZE = _TASK_UNIQUE + ACTOR_ID_SIZE  # 20
OBJECT_ID_SIZE = TASK_ID_SIZE + 4  # 24
NODE_ID_SIZE = 16
WORKER_ID_SIZE = 16
PLACEMENT_GROUP_ID_SIZE = 12


class BaseID:
    """Immutable fixed-width binary id."""

    SIZE = 0
    __slots__ = ("_bytes",)

    def __init__(self, binary: bytes):
        if not isinstance(binary, (bytes, bytearray)):
            raise TypeError(f"{type(self).__name__} expects bytes, got {type(binary)}")
        binary = bytes(binary)
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} expects {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._bytes == other._bytes

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._bytes))

    def __lt__(self, other) -> bool:
        return self._bytes < other._bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = JOB_ID_SIZE
    __slots__ = ()

    _counter_lock = threading.Lock()
    _counter = 0

    @classmethod
    def from_index(cls, index: int) -> "JobID":
        return cls(index.to_bytes(cls.SIZE, "little"))


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE
    __slots__ = ()


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE
    __slots__ = ()


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE
    __slots__ = ()


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(_ACTOR_UNIQUE) + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        """The actor id used for non-actor tasks of a job."""
        return cls(b"\x00" * _ACTOR_UNIQUE + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[_ACTOR_UNIQUE:])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE
    __slots__ = ()

    @classmethod
    def for_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(os.urandom(_TASK_UNIQUE) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        """The synthetic task id of a driver (owns driver-created objects)."""
        return cls(b"\xff" * _TASK_UNIQUE + ActorID.nil_for_job(job_id).binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[_TASK_UNIQUE:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE
    __slots__ = ()

    MAX_INDEX = 2**32 - 1

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        """The i-th return of `task_id` (index >= 1; 0 reserved for puts)."""
        if not 0 <= index <= cls.MAX_INDEX:
            raise ValueError(f"object index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts share the task id namespace; flip the high bit of the index
        # so put ids never collide with return ids.
        return cls(task_id.binary() + (0x80000000 | put_index).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_SIZE:], "little")

    def is_put(self) -> bool:
        return bool(self.index() & 0x80000000)

    def job_id(self) -> JobID:
        return self.task_id().job_id()
