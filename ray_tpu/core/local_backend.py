"""In-process eager backend (``local_mode=True``).

Reference: ``python/ray/_private/worker.py`` LOCAL_MODE — tasks run
synchronously in the driver; actors are plain in-process objects. Values
still round-trip through the serializer so local mode catches serialization
bugs, matching reference behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import execution, serialization
from ray_tpu.core.api import RuntimeBackend, Worker
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
)
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.refs import ObjectRef
from ray_tpu.core.task_spec import TaskSpec


class LocalBackend(RuntimeBackend):
    def __init__(self, num_cpus: float = 8, resources: Optional[Dict[str, float]] = None):
        self._resources = {"CPU": num_cpus, **(resources or {})}
        self._store: Dict[ObjectID, Any] = {}  # bytes | TaskError
        self._kv: Dict[bytes, bytes] = {}
        self._actors: Dict[ActorID, Any] = {}
        self._actor_locks: Dict[ActorID, threading.RLock] = {}
        self._dead_actors: Dict[ActorID, str] = {}
        self._named: Dict[Tuple[str, str], Tuple[ActorID, dict, Any]] = {}
        self._refcounts: Dict[ObjectID, int] = {}
        self._streams: Dict[bytes, Any] = {}
        self._lock = threading.RLock()
        self._worker: Optional[Worker] = None

    def bind_worker(self, worker: Worker) -> None:
        self._worker = worker

    # ---- objects -------------------------------------------------------
    def put_object(self, object_id: ObjectID, value: serialization.SerializedValue) -> None:
        with self._lock:
            self._store[object_id] = value.to_bytes()

    def _store_result(self, object_id: ObjectID, value: Any) -> None:
        if isinstance(value, TaskError):
            self._store[object_id] = value
        else:
            self._store[object_id] = serialization.serialize(value).to_bytes()

    def _lookup(self, ref: ObjectRef) -> Any:
        with self._lock:
            data = self._store.get(ref.id())
        if data is None:
            raise KeyError(f"object {ref.hex()} not found (local mode)")
        if isinstance(data, Exception):
            return data
        return serialization.deserialize_bytes(data)

    def get_objects(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        return [self._lookup(r) for r in refs]

    def wait(self, refs, num_returns, timeout, fetch_local):
        with self._lock:
            ready = [r for r in refs if r.id() in self._store]
        ready = ready[:num_returns]
        not_ready = [r for r in refs if r not in ready]
        return ready, not_ready

    def free(self, object_ids: Sequence[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                self._store.pop(oid, None)

    def add_local_ref(self, ref: ObjectRef) -> None:
        object_id = ref.id()
        with self._lock:
            self._refcounts[object_id] = self._refcounts.get(object_id, 0) + 1

    def remove_local_ref(self, ref: ObjectRef) -> None:
        object_id = ref.id()
        with self._lock:
            n = self._refcounts.get(object_id, 0) - 1
            if n <= 0:
                self._refcounts.pop(object_id, None)
                self._store.pop(object_id, None)
            else:
                self._refcounts[object_id] = n

    # ---- tasks ---------------------------------------------------------
    def _get_ref_value(self, ref: ObjectRef) -> Any:
        value = self._lookup(ref)
        if isinstance(value, Exception):
            raise value
        return value

    def submit_task(self, spec: TaskSpec) -> None:
        fn = self._worker.fn_table.load(spec.function_id)
        try:
            args, kwargs = execution.resolve_args(spec, self._get_ref_value)
        except TaskError as e:
            # Dependency failed: propagate to our returns (reference:
            # error propagation through lineage).
            with self._lock:
                for oid in spec.return_ids:
                    self._store[oid] = e
            if spec.num_returns == "streaming":
                stream = self._streams.get(spec.task_id.binary())
                if stream is not None:
                    stream.fail(e)
            return
        if spec.num_returns == "streaming":
            stream = self._streams[spec.task_id.binary()]
            count = 0
            try:
                for value in fn(*args, **kwargs):
                    count += 1
                    oid = ObjectID.from_index(spec.task_id, count)
                    with self._lock:
                        self._store_result(oid, value)
                    stream.append(count, oid)
            except Exception as e:  # noqa: BLE001
                stream.fail(TaskError(spec.name, e))
                return
            stream.complete(count)
            return
        results = execution.run_function(spec, fn, args, kwargs)
        with self._lock:
            for oid, value in results:
                self._store_result(oid, value)

    # ---- streaming ------------------------------------------------------
    def create_stream(self, spec: TaskSpec):
        from ray_tpu.core.streaming import ObjectRefStream

        stream = ObjectRefStream(spec.task_id.binary())
        self._streams[spec.task_id.binary()] = stream
        return stream

    def stream_next(self, task_id: bytes, index: int, timeout):
        from ray_tpu.core.streaming import _END

        stream = self._streams.get(task_id)
        if stream is None:
            raise RuntimeError("unknown stream")
        out = stream.next_blocking(index, timeout)
        if out is _END:
            self._streams.pop(task_id, None)
        return out

    def abandon_stream(self, task_id: bytes, consumed_pos: int) -> None:
        """Drop a partially-consumed stream: free undelivered items."""
        stream = self._streams.pop(task_id, None)
        if stream is None:
            return
        with stream._cond:
            undelivered = [
                oid for idx, oid in stream._items.items() if idx > consumed_pos
            ]
        with self._lock:
            for oid in undelivered:
                if oid not in self._refcounts:
                    self._store.pop(oid, None)

    # ---- actors --------------------------------------------------------
    def create_actor(self, spec: TaskSpec) -> None:
        cls = self._worker.fn_table.load(spec.function_id)
        name_key = None
        if spec.actor_name:
            name_key = (spec.namespace or "", spec.actor_name)
            with self._lock:
                if name_key in self._named:
                    raise ValueError(
                        f"actor name {spec.actor_name!r} already taken in "
                        f"namespace {spec.namespace!r}"
                    )
        try:
            args, kwargs = execution.resolve_args(spec, self._get_ref_value)
            instance = cls(*args, **kwargs)
        except Exception as e:  # noqa: BLE001
            self._dead_actors[spec.actor_id] = f"creation failed: {e!r}"
            return
        with self._lock:
            self._actors[spec.actor_id] = instance
            self._actor_locks[spec.actor_id] = threading.RLock()
            if name_key:
                self._named[name_key] = (spec.actor_id, spec.method_opts, spec.owner)

    def submit_actor_task(self, spec: TaskSpec) -> None:
        aid = spec.actor_id
        streaming = spec.num_returns == "streaming"
        stream = self._streams.get(spec.task_id.binary()) if streaming else None
        with self._lock:
            instance = self._actors.get(aid)
        if instance is None:
            reason = self._dead_actors.get(aid, "actor not found")
            err = ActorDiedError(aid, reason)
            with self._lock:
                for oid in spec.return_ids:
                    self._store[oid] = err
            if stream is not None:
                # streaming specs have no return ids: the error must reach
                # the stream or the generator blocks forever (the hang the
                # round-5 advisor flagged)
                stream.fail(err)
            return
        if spec.method_name == "__ray_ready__":
            with self._lock:
                self._store_result(spec.return_ids[0], True)
            return
        if spec.method_name == "__ray_terminate__":
            self.kill_actor(aid, no_restart=True)
            with self._lock:
                self._store_result(spec.return_ids[0], None)
            return
        fn = getattr(instance, spec.method_name)
        try:
            args, kwargs = execution.resolve_args(spec, self._get_ref_value)
        except TaskError as e:
            with self._lock:
                for oid in spec.return_ids:
                    self._store[oid] = e
            if stream is not None:
                stream.fail(e)
            return
        if streaming:
            if stream is None:
                raise RuntimeError(
                    "streaming actor task submitted without create_stream"
                )
            # mirror submit_task's streaming branch: iterate the generator
            # eagerly (local mode is eager), feeding the stream item ids
            count = 0
            try:
                with self._actor_locks[aid]:
                    for value in fn(*args, **kwargs):
                        count += 1
                        oid = ObjectID.from_index(spec.task_id, count)
                        with self._lock:
                            self._store_result(oid, value)
                        stream.append(count, oid)
            except Exception as e:  # noqa: BLE001
                stream.fail(TaskError(spec.name, e))
                return
            stream.complete(count)
            return
        with self._actor_locks[aid]:
            results = execution.run_function(spec, fn, args, kwargs)
        with self._lock:
            for oid, value in results:
                self._store_result(oid, value)

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        with self._lock:
            self._actors.pop(actor_id, None)
            self._actor_locks.pop(actor_id, None)
            self._dead_actors[actor_id] = "killed via kill()"
            self._named = {
                k: v for k, v in self._named.items() if v[0] != actor_id
            }

    def cancel(self, ref: ObjectRef, force: bool, recursive: bool) -> None:
        pass  # everything already ran (eager local mode)

    def get_named_actor(self, name: str, namespace: str):
        with self._lock:
            return self._named.get((namespace or "", name))

    def list_named_actors(self, all_namespaces: bool) -> List[Any]:
        with self._lock:
            if all_namespaces:
                return [
                    {"name": k[1], "namespace": k[0]} for k in self._named
                ]
            ns = self._worker.namespace if self._worker else ""
            return [{"name": k[1], "namespace": k[0]} for k in self._named if k[0] == ns]

    # ---- placement groups (trivially satisfied in local mode) ----------
    def create_pg(self, pg_id: bytes, bundles, strategy: str, name: str = "") -> None:
        with self._lock:
            if not hasattr(self, "_pgs"):
                self._pgs = {}
                self._named_pgs = {}
            self._pgs[pg_id] = {"state": "CREATED", "bundles": bundles, "strategy": strategy, "name": name}
            if name:
                self._named_pgs[name] = pg_id

    def wait_pg_ready(self, pg_id: bytes, timeout) -> str:
        with self._lock:
            info = getattr(self, "_pgs", {}).get(pg_id)
        return info["state"] if info else "REMOVED"

    def remove_pg(self, pg_id: bytes) -> None:
        with self._lock:
            info = getattr(self, "_pgs", {}).get(pg_id)
            if info:
                info["state"] = "REMOVED"

    def get_pg(self, pg_id: bytes):
        with self._lock:
            return getattr(self, "_pgs", {}).get(pg_id)

    def get_named_pg(self, name: str):
        with self._lock:
            pg_id = getattr(self, "_named_pgs", {}).get(name)
            if pg_id is None:
                return None
            return {"pg_id": pg_id, "bundles": self._pgs[pg_id]["bundles"], "state": self._pgs[pg_id]["state"]}

    def pg_table(self):
        with self._lock:
            return {k.hex(): dict(v) for k, v in getattr(self, "_pgs", {}).items()}

    # ---- kv / cluster --------------------------------------------------
    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def kv_keys(self, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    def kv_del(self, key: bytes) -> None:
        with self._lock:
            self._kv.pop(key, None)

    def cluster_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def available_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def nodes(self) -> List[Dict[str, Any]]:
        return [
            {
                "NodeID": "local",
                "Alive": True,
                "Resources": dict(self._resources),
            }
        ]

    def shutdown(self) -> None:
        with self._lock:
            self._store.clear()
            self._actors.clear()
            self._named.clear()
