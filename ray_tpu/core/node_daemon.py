"""Per-node daemon (raylet equivalent).

Reference: ``src/ray/raylet/`` — the node-local authority owning the shm
object store thread (``object_manager/object_manager.cc:28-41``), the
worker pool with startup tokens (``worker_pool.h:83``), the lease protocol
(``NodeManager::HandleRequestWorkerLease``, ``node_manager.cc:1797``),
local + spillback scheduling, placement-group bundle reservation 2PC
(``placement_group_resource_manager.{h,cc}``), and node-to-node object
transfer (``object_manager/``: pull/push with 5 MiB chunks).

Design notes vs. the reference:
  * Leases are granted against fixed-point local resources; when the local
    node can't fit (or exceeds the hybrid threshold) the reply carries a
    *spillback* target chosen from the controller-synced cluster view —
    the submitter re-requests there, exactly like raylet spillback.
  * Object transfer is daemon↔daemon chunked RPC pull; POSIX shm unlink
    semantics stand in for plasma's pinning during reads.
  * Workers are spawned as ``python -m ray_tpu.core.worker_main`` with a
    spawn token; the pool correlates registration with purpose (idle pool
    vs. dedicated actor worker — reference dedicated workers).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import ActorID, NodeID, ObjectID
from ray_tpu.core.object_store import ShmStore
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.rpc import RpcClient, RpcServer, ServerConnection
from ray_tpu.core.scheduling_policies import (
    feasible_anywhere,
    fits,
    pick_node_hybrid,
    utilization,
)
from ray_tpu.core.task_spec import DefaultScheduling, PlacementGroupScheduling, TaskSpec

logger = logging.getLogger(__name__)


@dataclass
class WorkerProc:
    pid: int
    proc: subprocess.Popen
    token: str
    host: str = ""
    port: int = 0
    registered: bool = False
    leased: bool = False
    claimed: bool = False  # a pending _pop_worker will take this worker
    actor_id: Optional[ActorID] = None
    # resources held by a dedicated actor worker, released on its death
    actor_resources: Optional[Dict[str, float]] = None
    actor_bundle_key: Optional[Tuple[bytes, int]] = None
    tpu_chips: Optional[List[int]] = None  # chip ids assigned to this worker
    conn: Optional[ServerConnection] = None
    client: Optional[RpcClient] = None
    idle_since: float = 0.0  # monotonic ts when last parked in the idle pool
    # CPU resources this worker holds that are currently RELEASED back to
    # the node pool because it blocks in a sync get/arg-fetch (reference:
    # NotifyDirectCallTaskBlocked). Stays set past an unblock that can't
    # re-acquire (bounded oversubscription); the lease/actor release
    # withholds exactly this amount so accounting always balances.
    blocked_released: Optional[Dict[str, float]] = None


@dataclass
class Lease:
    lease_id: int
    resources: Dict[str, float]
    worker: WorkerProc
    bundle_key: Optional[Tuple[bytes, int]] = None
    tpu_chips: Optional[List[int]] = None


@dataclass
class _ViewNode:
    node_id: bytes
    host: str
    port: int
    total: Dict[str, float]
    available: Dict[str, float]
    labels: Dict[str, str]


class NodeDaemon:
    def __init__(
        self,
        controller_host: str,
        controller_port: int,
        *,
        resources: Optional[Dict[str, float]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        session_dir: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.node_id = NodeID.from_random()
        self.host = host
        self.server = RpcServer(host, port)
        #: highest controller incarnation epoch this daemon has seen;
        #: the server-side fencing gate rejects writes stamped lower
        #: (a deposed controller double-writing after a takeover)
        self._controller_epoch_seen = 0
        self.server.epoch_gate = self._controller_epoch_gate
        # retry-by-default toward the control plane: every mutating call
        # is dedup-stamped (core/rpc.py), so surviving a controller
        # restart or a chaos'd reply is a transparent retry, not an error
        self.controller = RpcClient(
            controller_host, controller_port, name="controller",
            default_retries=GLOBAL_CONFIG.rpc_max_retries,
            role="controller",
        )
        self.controller_addr = (controller_host, controller_port)
        res = dict(resources or {})
        res.setdefault("CPU", float(os.cpu_count() or 1))
        merged_labels = dict(labels or {})
        # Accelerator autodetection (reference: raylet consults the
        # accelerator registry at startup). Explicit user resources win.
        if "TPU" not in res:
            try:
                from ray_tpu.accelerators import detect_node_accelerators

                auto_res, auto_labels = detect_node_accelerators()
                for k, v in auto_res.items():
                    res.setdefault(k, v)
                for k, v in auto_labels.items():
                    merged_labels.setdefault(k, v)
            except Exception:
                logger.debug("accelerator autodetection failed", exc_info=True)
        self.resources = NodeResources(ResourceSet(res), labels=merged_labels or None)
        # Node-wide TPU chip-id pool: every worker holding TPU resources
        # gets concrete chip ids (TPU_VISIBLE_CHIPS isolation).
        self._tpu_chips_free: List[int] = list(range(int(res.get("TPU", 0))))
        self.store = ShmStore()
        from ray_tpu.core.pull_manager import PullManager

        self.pulls = PullManager(self.store, self._peer)
        self.session_dir = session_dir or f"/tmp/ray_tpu/session_{os.getpid()}"
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.workers: Dict[str, WorkerProc] = {}  # token -> proc
        self.idle: List[WorkerProc] = []
        self.leases: Dict[int, Lease] = {}
        self._lease_counter = 0
        self._pending_actor_specs: Dict[str, TaskSpec] = {}  # token -> spec
        self._bundle_pools: Dict[Tuple[bytes, int], NodeResources] = {}
        self._prepared_bundles: Dict[Tuple[bytes, int], Dict[str, float]] = {}
        self._view: List[_ViewNode] = []
        self._peer_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._tasks: List[asyncio.Task] = []
        self._capacity_event = asyncio.Event()
        # lease requests currently parked on capacity (autoscaler demand)
        self._waiting_leases: Dict[int, Dict[str, float]] = {}
        self._waiting_seq = 0
        self._last_oom_check = 0.0
        self._stopping = False
        # relocation reports already delivered to the controller: its
        # directory is in-memory only, so a restarted controller needs
        # them REPLAYED after re-registration or owners mid-fetch would
        # fall back to lineage reconstruction (bounded ring)
        self._reported_moves: List[Dict[str, Any]] = []
        # cluster KV-tier registry: chain-digest hex -> {"desc", "expiry"}
        # (oldest-put first; refreshed to MRU on every get). The DAEMON
        # owns tier entries — they survive the replica process that
        # published them, which is the whole warm-restart story; TTL/cap
        # eviction (and the object delete that goes with it) runs in
        # _reap_loop.
        self._kv_tier: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._last_kv_tier_sweep = 0.0
        # drain protocol state (graceful preemption; see drain())
        self._draining = False
        self._drain_task: Optional[asyncio.Task] = None
        #: hook the hosting process installs (node_main) so a completed
        #: drain exits the process; None for in-process daemons (tests)
        self.on_drained = None
        for name in [m for m in dir(self) if m.startswith("d_")]:
            self.server.register(name[2:], getattr(self, name))

    # ---- lifecycle -----------------------------------------------------
    async def start(self) -> int:
        port = await self.server.start()
        self.port = port
        # hang defense: a blocked daemon loop freezes leases/object pulls
        # for every worker on this node — watchdog it
        from ray_tpu.observability.event_stats import install_loop_monitor

        install_loop_monitor(asyncio.get_event_loop(), "node_daemon")
        self._start_metrics()
        await self._register_with_controller(port)
        self._tasks.append(asyncio.ensure_future(self._sync_loop()))
        self._tasks.append(asyncio.ensure_future(self._reap_loop()))
        self._tasks.append(asyncio.ensure_future(self._log_tail_loop()))
        # Prestart (reference WorkerPool prestart): warm the pool so the
        # first wave of leases skips cold-start latency.
        for _ in range(GLOBAL_CONFIG.num_initial_workers):
            self._spawn_worker()
        if GLOBAL_CONFIG.preemption_probe_period_s > 0:
            self._tasks.append(asyncio.ensure_future(self._preemption_probe_loop()))
        return port

    # ---- drain protocol (graceful preemption) --------------------------
    async def _preemption_probe_loop(self) -> None:
        """Poll the pluggable maintenance-event probe (GCE metadata by
        default, injectable via accelerators.tpu.set_metadata_fetcher);
        an imminent event self-initiates drain — the SIGTERM-less half of
        preemption detection (host maintenance warns via metadata first)."""
        from ray_tpu.accelerators.tpu import maintenance_event_imminent

        loop = asyncio.get_event_loop()
        while not self._stopping and not self._draining:
            await asyncio.sleep(GLOBAL_CONFIG.preemption_probe_period_s)
            try:
                # the probe does blocking I/O (metadata HTTP) — keep it
                # off the daemon's event loop
                imminent = await loop.run_in_executor(None, maintenance_event_imminent)
            except Exception:
                continue
            if imminent:
                self.start_drain("maintenance event imminent")
                return

    def start_drain(self, reason: str) -> None:
        """Idempotently kick off the drain sequence (callable from signal
        handlers, the probe loop, and the ``drain`` RPC)."""
        if self._draining or self._stopping:
            return
        self._draining = True
        logger.warning("node %s draining: %s", self.node_id.hex()[:8], reason)
        # wake parked lease requests so they re-evaluate → spillback away
        self._notify_capacity()
        self._drain_task = asyncio.ensure_future(self._drain(reason))

    async def d_drain(self, payload, conn):
        """Drain RPC (reference GCS ``DrainNode`` delivered to the
        raylet): stop accepting work, finish what's running within the
        grace, replicate primary object copies off-node, exit cleanly."""
        self.start_drain(payload.get("reason", "drain RPC"))
        return {"ok": True, "draining": True}

    async def _drain(self, reason: str) -> None:
        from ray_tpu.core.deadline import Deadline

        deadline = Deadline.after(GLOBAL_CONFIG.drain_grace_s)
        # 1. self-report: the controller pulls us from the scheduling pool
        #    and pushes the DRAINING event to subscribed drivers/libraries
        try:
            await self.controller.call(
                "drain_node",
                {"node_id": self.node_id.binary(), "reason": reason},
                timeout=5,
            )
        except Exception:
            logger.warning("drain self-report failed", exc_info=True)
        # 2. let running work finish: leases (tasks) drain by completing;
        #    actors drain when their library controller migrates/kills
        #    them (Serve unroutes, Train checkpoints then fails over on
        #    node death). Poll — both counts only shrink now.
        while not deadline.expired and not self._stopping:
            busy_actors = sum(1 for w in self.workers.values() if w.actor_id is not None)
            if not self.leases and not busy_actors:
                break
            await asyncio.sleep(0.1)
        if self.leases:
            logger.warning(
                "drain grace expired with %d lease(s) still running — "
                "falling back to abrupt teardown", len(self.leases),
            )
        # 3. replicate primary shm copies to a peer so consumers re-fetch
        #    instead of paying lineage reconstruction (bounded by the
        #    remaining grace; best-effort)
        if GLOBAL_CONFIG.drain_flush_objects and not self._stopping:
            try:
                await self._flush_objects(deadline)
            except Exception:
                logger.warning("drain object flush failed", exc_info=True)
        # 3b. the grace is spent: any worker still hosting an actor or a
        #    running lease is in the documented abrupt-death fallback —
        #    reap it BEFORE deregistering. Deregistration makes the
        #    controller restart our actors (and resubmit our tasks)
        #    elsewhere immediately; a stale worker that outlives it can
        #    still answer pushes from clients with cached addresses, so
        #    one actor briefly has TWO live incarnations — the old one
        #    answering a call the new one should get (the test_drain
        #    pid2==pid1 flake: the budget-free restart happened, but the
        #    not-yet-reaped old worker answered first), and a task
        #    re-executed elsewhere can double its side effects.
        stale = [
            w.proc
            for w in self.workers.values()
            if w.actor_id is not None or w.leased
        ]
        if stale and not self._stopping:
            from ray_tpu.util.reaper import reap_all

            await asyncio.get_event_loop().run_in_executor(
                None, lambda: reap_all(stale)
            )
        # 4. deregister: the controller fails our remaining actors over
        #    budget-free NOW instead of waiting out the health checker
        try:
            await self.controller.call(
                "deregister_node",
                {"node_id": self.node_id.binary(), "reason": f"drained: {reason}"},
                timeout=5,
            )
        except Exception:
            logger.warning("drain deregister failed", exc_info=True)
        logger.info("drain complete (%s)", reason)
        if self.on_drained is not None:
            try:
                self.on_drained()
            except Exception:
                pass

    async def _flush_objects(self, deadline) -> None:
        """Ask a live peer daemon to pull every local primary copy, then
        record the relocations with the controller (the owner-side fetch
        fallback consults that directory when our copies vanish)."""
        peers = [
            n for n in self._view if n.node_id != self.node_id.binary()
        ]
        if not peers:
            return
        # primaries only: transfer-received replicas already live on
        # their source node — re-replicating them burns the bounded grace
        # and pollutes the relocation ring for no added durability.
        # Unsealed entries are mid-receive and not ours to replicate.
        entries = [
            e
            for e in self.store.list_entries()
            if e.get("primary", True) and e.get("sealed", True)
        ]
        if not entries:
            return
        moves: List[Dict[str, Any]] = []
        for i, entry in enumerate(entries):
            if deadline.expired or self._stopping:
                logger.warning(
                    "drain flush ran out of grace: %d/%d objects replicated",
                    len(moves), len(entries),
                )
                break
            peer = peers[i % len(peers)]
            object_id = bytes.fromhex(entry["object_id"])  # list_entries is hex
            try:
                meta = await self._peer(peer.host, peer.port).call(
                    "pull_object",
                    {
                        "object_id": object_id,
                        "sources": [(self.host, self.port)],
                    },
                    timeout=max(1.0, min(60.0, deadline.remaining())),
                )
            except Exception:
                logger.warning(
                    "drain flush of %s to %s:%s failed",
                    object_id.hex()[:12], peer.host, peer.port, exc_info=True,
                )
                continue
            if meta is not None and meta.get("segment"):
                moves.append(
                    {
                        "object_id": object_id,
                        "node_id": peer.node_id,
                        "host": peer.host,
                        "port": peer.port,
                    }
                )
                # the peer holds the replica now: stop claiming the
                # object so our shutdown doesn't unlink the (possibly
                # shared-inode) segment out from under it
                self.store.forget(ObjectID(object_id))
        if moves:
            await self.controller.call(
                "report_relocated", {"moves": moves}, timeout=10
            )
            # remember what we told the controller: a controller restart
            # mid-drain loses the directory, and the re-register path
            # replays these (bounded like the controller-side ring)
            self._reported_moves.extend(moves)
            del self._reported_moves[:-4096]
            logger.info("drain: replicated %d object(s) off-node", len(moves))

    # ---- memory monitor (OOM killer) -----------------------------------
    @staticmethod
    def _memory_available_fraction() -> float:
        """MemAvailable/MemTotal from /proc/meminfo (no psutil dep)."""
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, rest = line.partition(":")
                    info[k] = int(rest.strip().split()[0])
            return info["MemAvailable"] / max(1, info["MemTotal"])
        except Exception:
            return 1.0  # unknown platform: never trigger

    def _oom_check(self, available_fraction: Optional[float] = None) -> Optional[WorkerProc]:
        """Reference ``MemoryMonitor`` + ``WorkerKillingPolicy``: when the
        node runs out of memory, kill the NEWEST leased pooled worker
        (newest-first loses the least progress; reference FIFO policy).
        The owner resubmits the task if it has retries left — a task
        submitted with max_retries=0 fails as WorkerCrashedError, the
        same contract as any worker death. Returns the victim (already
        terminated) or None."""
        if not GLOBAL_CONFIG.memory_monitor_enabled:
            return None
        frac = (
            available_fraction
            if available_fraction is not None
            else self._memory_available_fraction()
        )
        if frac >= GLOBAL_CONFIG.memory_monitor_min_available_fraction:
            return None
        leased = [
            l.worker
            for l in sorted(self.leases.values(), key=lambda l: l.lease_id)
            if l.worker.actor_id is None
        ]
        if not leased:
            return None
        victim = leased[-1]  # newest lease = least progress lost
        logger.warning(
            "memory monitor: available fraction %.3f below %.3f — killing "
            "newest task worker pid=%d",
            frac, GLOBAL_CONFIG.memory_monitor_min_available_fraction, victim.pid,
        )
        try:
            victim.proc.kill()
        except Exception:
            pass
        return victim

    async def _register_with_controller(self, port: int) -> None:
        await self.controller.call(
            "register_node",
            {
                "node_id": self.node_id.binary(),
                "host": self.host,
                "port": port,
                "resources": self.resources.total.to_dict(),
                "labels": self.resources.labels,
                # held PG bundles: a restarted controller re-adopts these
                # instead of double-reserving the PG elsewhere
                "bundles": [
                    {
                        "pg_id": key[0],
                        "bundle_index": key[1],
                        "resources": pool.total.to_dict(),
                    }
                    for key, pool in self._bundle_pools.items()
                ],
            },
            retries=GLOBAL_CONFIG.rpc_max_retries,
        )

    def _start_metrics(self) -> None:
        """Prometheus /metrics endpoint (reference ``metrics_agent.py`` →
        ``prometheus_exporter.py``; system metrics per ``metric_defs.cc``)."""
        if not GLOBAL_CONFIG.metrics_export_enabled:
            self.metrics_port = 0
            return
        from ray_tpu.observability.metrics import Gauge, MetricsServer, on_collect

        nid = self.node_id.hex()[:12]
        g_store_used = Gauge("raytpu_object_store_used_bytes", "shm store bytes in use", ("node",))
        g_store_objs = Gauge("raytpu_object_store_num_objects", "objects in the shm store", ("node",))
        g_spilled = Gauge("raytpu_object_store_num_spilled", "objects spilled to disk", ("node",))
        g_workers = Gauge("raytpu_workers", "worker processes", ("node", "state"))
        g_leases = Gauge("raytpu_active_leases", "granted worker leases", ("node",))
        g_avail = Gauge("raytpu_resource_available", "available resource capacity", ("node", "resource"))

        def sample() -> None:
            st = self.store.stats()
            labels = {"node": nid}
            g_store_used.set(st["used_bytes"], labels)
            g_store_objs.set(st["num_objects"], labels)
            g_spilled.set(st["num_spilled"], labels)
            g_workers.set(len(self.workers), {"node": nid, "state": "total"})
            g_workers.set(len(self.idle), {"node": nid, "state": "idle"})
            g_leases.set(len(self.leases), labels)
            for res, val in self.resources.available.to_dict().items():
                g_avail.set(val, {"node": nid, "resource": res})

        self._metrics_cb = on_collect(sample)
        self._metrics_server = MetricsServer(host=GLOBAL_CONFIG.metrics_bind_host, port=GLOBAL_CONFIG.metrics_port)
        self.metrics_port = self._metrics_server.port
        logger.info("metrics at http://127.0.0.1:%d/metrics", self.metrics_port)

    async def stop(self) -> None:
        self._stopping = True
        from ray_tpu.observability.event_stats import remove_loop_monitor

        remove_loop_monitor(asyncio.get_event_loop())
        if getattr(self, "_metrics_server", None) is not None:
            from ray_tpu.observability.metrics import remove_collect

            remove_collect(self._metrics_cb)
            self._metrics_server.stop()
        for t in self._tasks:
            t.cancel()
        if self._drain_task is not None:
            self._drain_task.cancel()
        # Escalating reap of every child we spawned (hang defense): one
        # shared SIGTERM grace for the whole pool, SIGKILL the survivors —
        # a worker ignoring SIGTERM (stuck in native code, masked signal)
        # must not outlive its daemon and leak into the next session. Off
        # the event loop: wait() grace windows would block it.
        from ray_tpu.util.reaper import reap_all

        procs = [w.proc for w in self.workers.values()]
        if procs:
            survivors = await asyncio.get_event_loop().run_in_executor(
                None, lambda: reap_all(procs)
            )
            if survivors:
                logger.error("unreapable worker pids (D-state?): %s", survivors)
        await self.controller.close()
        for c in self._peer_clients.values():
            await c.close()
        self.store.shutdown()
        await self.server.stop()

    async def _log_tail_loop(self) -> None:
        """Tail this node's worker log files and forward new lines to the
        controller for driver display (reference ``LogMonitor``,
        ``_private/log_monitor.py:103``).

        Known limitation vs the reference: lines are not tagged with a
        job id, so in a multi-driver cluster every driver sees every
        worker's output (the reference filters per job)."""
        if not GLOBAL_CONFIG.log_to_driver:
            return
        import glob as _glob

        offsets: Dict[str, int] = {}
        logs_dir = os.path.join(self.session_dir, "logs")
        while not self._stopping:
            await asyncio.sleep(0.5)
            batch = []
            try:
                for path in _glob.glob(os.path.join(logs_dir, "worker-*.log")):
                    try:
                        size = os.path.getsize(path)
                        off = offsets.get(path, 0)
                        if size < off:
                            off = 0  # truncated/rotated: restart from top
                        if size == off:
                            offsets[path] = off
                            continue
                        with open(path, "rb") as f:
                            f.seek(off)
                            data = f.read(min(size - off, 1 << 16))
                        # advance only past COMPLETE lines — a partial
                        # tail line is re-read next tick, and nothing is
                        # ever skipped (the chunk bound paces big bursts
                        # across ticks instead of dropping them)
                        cut = data.rfind(b"\n")
                        if cut < 0:
                            offsets[path] = off
                            continue
                        offsets[path] = off + cut + 1
                        lines = data[: cut + 1].decode(errors="replace").splitlines()
                        if lines:
                            batch.append(
                                {
                                    "worker": os.path.basename(path),
                                    "lines": lines,
                                }
                            )
                    except OSError:
                        continue
                if batch:
                    await self.controller.call(
                        "worker_logs",
                        {"node_id": self.node_id.binary(), "batch": batch},
                        timeout=10,
                    )
            except Exception:
                pass  # forwarding is best-effort

    # ---- resource sync (ray_syncer) -----------------------------------
    async def _sync_loop(self) -> None:
        while not self._stopping:
            try:
                reply = await self.controller.call(
                    "sync_resources",
                    {
                        "node_id": self.node_id.binary(),
                        "available": self.resources.available.to_dict(),
                        "total": self.resources.total.to_dict(),
                        # store + worker counters: what cluster_status()
                        # reports per node without a fan-out RPC
                        "store": self.store.stats(),
                        "num_workers": len(self.workers),
                        "num_leases": len(self.leases),
                        # parked lease shapes: task demand for the
                        # autoscaler's bin-packing
                        "pending_leases": list(self._waiting_leases.values()),
                        # running actors: a restarted controller adopts
                        # these instead of re-scheduling them (GCS-restart
                        # reconciliation, reference raylet reconnect)
                        "actors": [
                            {
                                "actor_id": w.actor_id,
                                "host": w.host,
                                "port": w.port,
                                "pid": w.pid,
                            }
                            for w in self.workers.values()
                            if w.actor_id is not None and w.registered
                        ],
                    },
                    timeout=5,
                )
                # passive fencing-floor update: every sync reply carries
                # the current controller incarnation epoch
                self._note_controller_epoch(reply.get("controller_epoch", 0))
                if reply.get("unknown_node"):
                    # controller restarted and lost node membership:
                    # re-register (carrying held bundles for re-adoption)
                    # and replay unacked session state — the relocation
                    # reports live only in controller memory. Running
                    # actors replay themselves on the next sync's
                    # ``actors`` payload.
                    logger.info("controller does not know us — re-registering")
                    from ray_tpu.observability.rpc_metrics import (
                        CONTROLLER_RECONNECTS,
                    )

                    CONTROLLER_RECONNECTS.inc(labels={"role": "daemon"})
                    await self._register_with_controller(self.port)
                    if self._reported_moves:
                        await self.controller.call(
                            "report_relocated",
                            {"moves": list(self._reported_moves)},
                            timeout=10,
                        )
                    continue
                self._view = [
                    _ViewNode(
                        node_id=n["node_id"],
                        host=n["host"],
                        port=n["port"],
                        total=n["total"],
                        available=n["available"],
                        labels=n.get("labels", {}),
                    )
                    for n in reply["view"]
                ]
            except Exception:
                if not self._stopping:
                    logger.debug("resource sync failed", exc_info=True)
            await asyncio.sleep(0.2)

    # ---- TPU chip-id pool ----------------------------------------------
    def _allocate_tpu_chips(self, n: int) -> Optional[List[int]]:
        if n <= 0:
            return None
        if len(self._tpu_chips_free) < n:
            logger.warning(
                "TPU accounting says %d chips free but id pool has %d",
                n, len(self._tpu_chips_free),
            )
            return None
        chips = self._tpu_chips_free[:n]
        del self._tpu_chips_free[:n]
        return chips

    def _free_tpu_chips(self, chips: Optional[List[int]]) -> None:
        if chips:
            self._tpu_chips_free.extend(chips)
            self._tpu_chips_free.sort()

    # ---- worker pool ---------------------------------------------------
    def _spawn_worker(
        self,
        actor_spec: Optional[TaskSpec] = None,
        tpu_chips: Optional[List[int]] = None,
    ) -> WorkerProc:
        if self._stopping:
            # a lease racing shutdown must not spawn a worker the stop()
            # reap snapshot will never see (leak defense)
            raise RuntimeError("daemon is stopping")
        token = os.urandom(8).hex()
        log_path = os.path.join(self.session_dir, "logs", f"worker-{token}.log")
        log_f = open(log_path, "ab")
        env = dict(os.environ)
        env["RAY_TPU_SPAWN_TOKEN"] = token
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_DAEMON_ADDR"] = f"{self.host}:{self.port}"
        # explicit parent pid: the worker's orphan watch must not trust
        # os.getppid() captured at ITS boot — the daemon can die during
        # that window and the worker would memorize the reparented value
        env["RAY_TPU_DAEMON_PID"] = str(os.getpid())
        env["RAY_TPU_CONTROLLER_ADDR"] = f"{self.controller_addr[0]}:{self.controller_addr[1]}"
        # CPU workers: strip accelerator-tunnel env triggers (each one
        # starts a per-process relay client burning ~half a core — see
        # GlobalConfig.strip_child_env). TPU-assigned workers RESTORE the
        # values the daemon's own spawn stashed (the daemon env is
        # already scrubbed, so "keep" means un-stash, not skip-strip).
        from ray_tpu.core.config import restore_scrubbed_env, scrub_child_env

        chips = tpu_chips
        if chips is None:
            scrub_child_env(env)
            # Chip-less workers are pinned to CPU (hang defense): a bare
            # `import jax` in one would otherwise probe the TPU runtime —
            # minutes of instance-metadata retries on non-TPU hosts (the
            # round-5 "suite wedged" class), or grabbing every chip on a
            # real TPU host. A pooled worker later PROMOTED to TPU undoes
            # only THIS pin in w_set_accelerator_env (restoring whatever
            # the operator had set, "" = unset), before jax initializes.
            env["RAY_TPU_PREPIN_JAX_PLATFORMS"] = env.get("JAX_PLATFORMS") or ""
            env["JAX_PLATFORMS"] = "cpu"
        else:
            # TPU-assigned workers: an operator-set JAX_PLATFORMS passes
            # through untouched (same contract as the promotion path in
            # w_set_accelerator_env — the two chip-grant paths must not
            # place the same env on different devices); unset means jax
            # picks the TPU it was given.
            restore_scrubbed_env(env)
        # Dedicated actor workers get their chip isolation at spawn time —
        # before libtpu can initialize (TPU_VISIBLE_CHIPS + topology bounds,
        # reference accelerators/tpu.py:31).
        if chips is not None:
            from ray_tpu.accelerators.tpu import TPUAcceleratorManager

            env.update(TPUAcceleratorManager.isolation_env([str(c) for c in chips]))
        # Workers share the daemon's process group so a hard node kill
        # (killpg, cluster_utils.remove_node) takes them down too.
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main"],
            env=env,
            stdout=log_f,
            stderr=subprocess.STDOUT,
        )
        w = WorkerProc(pid=proc.pid, proc=proc, token=token)
        w.tpu_chips = chips
        self.workers[token] = w
        if actor_spec is not None:
            w.actor_id = actor_spec.actor_id
            self._pending_actor_specs[token] = actor_spec
        return w

    async def d_register_worker(self, payload, conn: ServerConnection):
        token = payload["token"]
        w = self.workers.get(token)
        if w is None:
            raise ValueError(f"unknown spawn token {token}")
        w.host, w.port = payload["host"], payload["port"]
        w.registered = True
        w.conn = conn
        conn.peer_tags["worker_token"] = token
        w.client = RpcClient(
            w.host, w.port, name=f"worker-{token[:6]}", role="worker"
        )
        spec = self._pending_actor_specs.pop(token, None)
        if spec is not None:
            asyncio.ensure_future(self._run_actor_creation(w, spec))
        elif not w.claimed:
            # Workers spawned by a waiting _pop_worker are claimed by that
            # lease — adding them to the idle pool too would double-grant
            # one worker to two leases (deadlock on its execution lane).
            w.idle_since = time.monotonic()
            self.idle.append(w)
        # always notify: a _pop_worker parked on ITS claimed spawn wakes
        # on this registration instead of its poll timeout (the lease
        # grant sits on the submit hot path during pump growth)
        self._notify_capacity()
        return {"node_id": self.node_id.binary()}

    async def _run_actor_creation(self, w: WorkerProc, spec: TaskSpec) -> None:
        try:
            await w.client.call("run_actor_creation", {"spec": spec}, timeout=None)
        except Exception as e:
            logger.warning("actor creation dispatch failed: %r", e)
            try:
                await self.controller.call(
                    "report_actor_death",
                    {"actor_id": spec.actor_id, "reason": f"worker failed: {e!r}"},
                )
            except Exception:
                pass

    async def _reap_loop(self) -> None:
        """Detect worker process deaths (reference: raylet notices socket
        close; here we also poll the pid)."""
        while not self._stopping:
            for token, w in list(self.workers.items()):
                code = w.proc.poll()
                if code is None:
                    continue
                del self.workers[token]
                if w in self.idle:
                    self.idle.remove(w)
                for lease_id, lease in list(self.leases.items()):
                    if lease.worker is w:
                        self._release_lease(lease_id)
                self._release_actor_resources(w)
                self._sweep_recycle_pool(w.proc.pid)
                if w.actor_id is not None:
                    try:
                        await self.controller.call(
                            "report_actor_death",
                            {
                                "actor_id": w.actor_id,
                                "reason": f"worker exited with code {code}",
                                # deaths during OUR drain are preemption
                                # casualties (incl. the pre-deregister
                                # reap of grace overstayers): restarts
                                # must stay budget-free, same as the
                                # deregistration-path failover
                                "drained": self._draining,
                            },
                        )
                    except Exception:
                        pass
            self._kill_idle_workers()
            self._sweep_orphan_pools()
            self._kv_tier_sweep()
            now = time.monotonic()
            if now - self._last_oom_check >= GLOBAL_CONFIG.memory_monitor_period_s:
                self._last_oom_check = now
                self._oom_check()
            await asyncio.sleep(0.1)

    @staticmethod
    def _sweep_recycle_pool(pid: int) -> None:
        """Unlink a dead worker's segment-reuse pool files (named
        ``rt-pool-<pid>-<n>`` by StoreClient.recycle) so they don't leak
        tmpfs memory past the process's lifetime."""
        import glob

        for path in glob.glob(f"/dev/shm/rt-pool-{pid}-*"):
            try:
                os.unlink(path)
            except OSError:
                pass

    _pool_orphan_sweep_period_s = 10.0
    _last_pool_orphan_sweep = 0.0

    def _sweep_orphan_pools(self) -> None:
        """Reap pool files whose owning pid is dead — covers DRIVERS and
        externally-started processes the worker-reap path never sees
        (SIGKILL'd drivers would otherwise shrink usable store capacity
        forever, since pool files count as used in admission control)."""
        import glob

        now = time.monotonic()
        if now - self._last_pool_orphan_sweep < self._pool_orphan_sweep_period_s:
            return
        self._last_pool_orphan_sweep = now
        # rt-pool-<pid>-* (segment reuse pools), rt-chan-<pid>-* (compiled
        # graph channels) and their sem.rt-chan-<pid>-* wakeup semaphores
        # all embed the owning pid
        for path in glob.glob("/dev/shm/rt-pool-*") + glob.glob(
            "/dev/shm/rt-chan-*"
        ) + glob.glob("/dev/shm/sem.rt-chan-*"):
            base = os.path.basename(path)
            if base.startswith("sem."):
                base = base[4:]
            try:
                pid = int(base.split("-")[2])
            except (IndexError, ValueError):
                continue
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            except PermissionError:
                pass  # pid alive under another uid

    def _kill_idle_workers(self) -> None:
        """Reference ``idle_worker_killing``: pooled workers idle past the
        deadline are retired (the floor of ``num_initial_workers`` stays
        warm)."""
        deadline = GLOBAL_CONFIG.idle_worker_killing_time_s
        if deadline <= 0:
            return
        now = time.monotonic()
        keep_floor = GLOBAL_CONFIG.num_initial_workers
        for w in list(self.idle):
            if len(self.idle) <= keep_floor:
                break
            if w.claimed or now - w.idle_since < deadline:
                continue
            self.idle.remove(w)
            try:
                w.proc.terminate()  # reap loop finishes the bookkeeping
            except Exception:
                pass

    # ---- leases (task scheduling) -------------------------------------
    async def d_request_lease(self, payload, conn):
        """The lease hot path (``HandleRequestWorkerLease``).

        Requests that can't be served *right now* are queued daemon-side
        (waiting on capacity/worker changes) rather than bounced back —
        client retry-polling collapses throughput under backlog (reference:
        raylet queues lease requests in the local task manager)."""
        request: Dict[str, float] = payload["resources"]
        strategy = payload.get("strategy")
        deadline = time.monotonic() + 30.0
        # visible to the resource sync → the AUTOSCALER's task-demand
        # signal (reference: resource_demand_scheduler reads queued
        # lease shapes from the load report)
        self._waiting_seq += 1
        wid = self._waiting_seq
        first = True
        grace_deadline = (
            time.monotonic() + GLOBAL_CONFIG.infeasible_lease_grace_s
        )
        try:
            while True:
                reply = await self._try_lease(request, strategy)
                if reply is not None:
                    if reply.get("infeasible") and time.monotonic() < grace_deadline:
                        # infeasible NOW ≠ infeasible forever: park so the
                        # autoscaler sees the demand; a joining node flips
                        # this to a grant/spillback
                        reply = None
                    else:
                        return reply
                if first:
                    first = False
                    self._waiting_leases[wid] = dict(request)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"retry_after": 0.05}
                try:
                    await asyncio.wait_for(
                        self._capacity_event.wait(), timeout=min(0.5, remaining)
                    )
                except (asyncio.TimeoutError, TimeoutError):
                    pass
        finally:
            self._waiting_leases.pop(wid, None)

    def _notify_capacity(self) -> None:
        """Wake queued lease requests (set() resolves current waiters even
        though we clear immediately — single-threaded loop)."""
        self._capacity_event.set()
        self._capacity_event.clear()

    async def _try_lease(self, request: Dict[str, float], strategy):
        """One grant attempt: dict reply, or None = queue and retry."""
        # Draining: no NEW leases land here — spill to a live peer (or
        # report infeasible so the client's retry window + autoscaler
        # replacement take over). PG-bundle leases are exempt: a committed
        # bundle exists only on this node, refusing would wedge the gang.
        if self._draining and not isinstance(strategy, PlacementGroupScheduling):
            reply = self._spillback_or_retry(request, strategy)
            return None if "retry_after" in reply else reply
        # Placement-group leases consume from the bundle pool.
        bundle_key = None
        if isinstance(strategy, PlacementGroupScheduling):
            bundle_key = self._find_bundle(strategy, request)
            if bundle_key is None:
                return None
            pool = self._bundle_pools[bundle_key]
            req = ResourceSet(request)
            pool.allocate(req)
        else:
            req = ResourceSet(request)
            if not self.resources.can_fit(req):
                reply = self._spillback_or_retry(request, strategy)
                return None if "retry_after" in reply else reply
            # hybrid: spill when local utilization is past the threshold
            if (
                self.resources.utilization() >= GLOBAL_CONFIG.scheduler_spread_threshold
                and len(self._view) > 1
            ):
                alt = self._pick_remote(request, strategy)
                if alt is not None and alt.node_id != self.node_id.binary():
                    return {"spillback": (alt.host, alt.port)}
            self.resources.allocate(req)

        worker = await self._pop_worker()
        if worker is None:
            if bundle_key is not None:
                self._bundle_pools[bundle_key].release(ResourceSet(request))
            else:
                self.resources.release(ResourceSet(request))
            return None
        worker.leased = True
        self._lease_counter += 1
        lease = Lease(self._lease_counter, request, worker, bundle_key)
        # TPU isolation for pooled workers: assign chip ids and tell the
        # worker before any task lands on it. A worker that holds chips is
        # chip-BOUND for its lifetime (libtpu can't rebind after init), so
        # it is retired — not pooled — when the lease ends; failure to
        # isolate fails the lease rather than granting an unisolated one.
        if request.get("TPU", 0) >= 1 and worker.tpu_chips is None:
            chips = self._allocate_tpu_chips(int(request["TPU"]))
            ok = False
            if chips is not None and worker.client is not None:
                try:
                    await worker.client.call(
                        "set_accelerator_env",
                        {"resource": "TPU", "ids": chips},
                        timeout=5,
                    )
                    ok = True
                except Exception:
                    logger.warning("set_accelerator_env failed", exc_info=True)
            if not ok:
                self._free_tpu_chips(chips)
                worker.leased = False
                if worker not in self.idle:
                    worker.idle_since = time.monotonic()
                    self.idle.append(worker)
                if bundle_key is not None:
                    self._bundle_pools[bundle_key].release(ResourceSet(request))
                else:
                    self.resources.release(ResourceSet(request))
                return None
            worker.tpu_chips = chips
            lease.tpu_chips = chips
        self.leases[lease.lease_id] = lease
        return {
            "grant": {
                "lease_id": lease.lease_id,
                "host": worker.host,
                "port": worker.port,
                "node_id": self.node_id.binary(),
            }
        }

    def _find_bundle(self, strategy: PlacementGroupScheduling, request) -> Optional[Tuple[bytes, int]]:
        if strategy.bundle_index >= 0:
            key = (strategy.pg_id, strategy.bundle_index)
            pool = self._bundle_pools.get(key)
            if pool is not None and pool.can_fit(ResourceSet(request)):
                return key
            return None
        for key, pool in self._bundle_pools.items():
            if key[0] == strategy.pg_id and pool.can_fit(ResourceSet(request)):
                return key
        return None

    def _spillback_or_retry(self, request, strategy):
        alt = self._pick_remote(request, strategy)
        if alt is not None and alt.node_id != self.node_id.binary():
            return {"spillback": (alt.host, alt.port)}
        if self._view and not feasible_anywhere(self._view, request):
            return {"infeasible": True}
        return {"retry_after": 0.05}

    def _pick_remote(self, request, strategy):
        return pick_node_hybrid(
            self._view,
            request,
            strategy if strategy is not None else DefaultScheduling(),
            local_node_id=self.node_id.binary(),
            spread_threshold=GLOBAL_CONFIG.scheduler_spread_threshold,
        )

    async def _pop_worker(self) -> Optional[WorkerProc]:
        while self.idle:
            w = self.idle.pop()
            if w.proc.poll() is None and w.registered:
                return w
        # cold start (startup token accounting: bounded concurrent spawns)
        starting = sum(
            1 for w in self.workers.values() if not w.registered and w.actor_id is None
        )
        if starting >= GLOBAL_CONFIG.worker_maximum_startup_concurrency:
            return None
        w = self._spawn_worker()
        w.claimed = True
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if w.registered:
                w.claimed = False
                return w
            if w.proc.poll() is not None:
                w.claimed = False
                return None
            # event-driven: d_register_worker notifies capacity, so the
            # grant fires the moment the worker registers — the timeout
            # only paces the liveness re-check of the spawned process
            try:
                await asyncio.wait_for(self._capacity_event.wait(), timeout=0.05)
            except (asyncio.TimeoutError, TimeoutError):
                pass
        # spawn timed out: release the claim; if it registered late, give
        # it to the idle pool so it isn't orphaned
        w.claimed = False
        if w.registered and not w.leased and w not in self.idle:
            w.idle_since = time.monotonic()
            self.idle.append(w)
        return None

    async def d_return_lease(self, payload, conn):
        self._release_lease(payload["lease_id"])
        return True

    # ---- blocked-worker resource release (reference raylet
    # NotifyDirectCallTaskBlocked/Unblocked) --------------------------------
    # A worker parked in a sync get/arg-fetch holds CPUs it cannot use —
    # the PR 10 scheduling deadlock: every CPU held by consume tasks
    # blocked on producers that NEED a CPU to (re)run. While blocked, the
    # CPU share of the worker's lease (or actor allocation) goes back to
    # the node pool; on wake it is re-acquired when it fits, otherwise
    # the task finishes briefly oversubscribed and the lease release
    # withholds the already-returned amount. TPU chips are never
    # released: a chip-bound process can't lend its chips.

    def _worker_held_node_resources(self, w: WorkerProc) -> Optional[Dict[str, float]]:
        """The resources ``w`` holds from the NODE pool (bundle-pool
        allocations are excluded — a PG bundle's capacity is not the
        node's to lend)."""
        if w.actor_id is not None:
            if w.actor_resources is not None and w.actor_bundle_key is None:
                return w.actor_resources
            return None
        for lease in self.leases.values():
            if lease.worker is w and lease.bundle_key is None:
                return lease.resources
        return None

    async def d_worker_blocked(self, payload, conn):
        """The worker entered a blocking sync get/arg-fetch: release the
        CPU share of what it holds so other work (e.g. the producer it
        waits on) can be scheduled here. Idempotent per block episode."""
        if not GLOBAL_CONFIG.blocked_worker_resource_release:
            return False
        w = self.workers.get(payload.get("token", ""))
        if w is None or w.blocked_released is not None:
            return False
        held = self._worker_held_node_resources(w)
        cpu = (held or {}).get("CPU", 0.0)
        if cpu <= 0:
            return False
        rel = {"CPU": cpu}
        self.resources.release(ResourceSet(rel))
        w.blocked_released = rel
        self._notify_capacity()
        return True

    async def d_worker_unblocked(self, payload, conn):
        """The worker woke up: re-acquire the released CPUs when they
        fit. When they don't (another task took them meanwhile), the
        task continues oversubscribed and the eventual lease/actor
        release withholds the debt — accounting self-heals even if this
        RPC is lost entirely."""
        w = self.workers.get(payload.get("token", ""))
        if w is None or w.blocked_released is None:
            return False
        rel = ResourceSet(w.blocked_released)
        if self.resources.can_fit(rel):
            self.resources.allocate(rel)
            w.blocked_released = None
            return True
        return False

    def _withhold_blocked_release(self, w: WorkerProc, req: ResourceSet) -> ResourceSet:
        """Subtract the CPUs already returned to the pool while ``w``
        blocked from what a lease/actor release would give back."""
        if w.blocked_released is None:
            return req
        rel, w.blocked_released = w.blocked_released, None
        return req.subtract(ResourceSet(rel), allow_negative=True)

    def _release_lease(self, lease_id: int) -> None:
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        req = ResourceSet(lease.resources)
        if lease.bundle_key is not None:
            pool = self._bundle_pools.get(lease.bundle_key)
            if pool is not None:
                pool.release(req)
        else:
            self.resources.release(
                self._withhold_blocked_release(lease.worker, req)
            )
        w = lease.worker
        w.leased = False
        self._notify_capacity()
        if w.tpu_chips is not None and w.actor_id is None:
            # Chip-bound pooled worker: libtpu is (possibly) initialized on
            # these chips, so the process can never serve a different chip
            # set. Retire it; the reap loop frees its chips.
            try:
                w.proc.terminate()
            except Exception:
                pass
            return
        if w.proc.poll() is None and w.registered and w.actor_id is None and w not in self.idle:
            w.idle_since = time.monotonic()
            self.idle.append(w)

    # ---- actors --------------------------------------------------------
    async def d_start_actor(self, payload, conn):
        if self._draining:
            # races the controller's DRAINING exclusion: reschedule
            raise RuntimeError("node is draining; cannot host new actors")
        spec: TaskSpec = payload["spec"]
        # Exactly-once guard for control-plane replays (a restarted
        # controller rescheduling an actor it only half-persisted, or a
        # dedup-window miss): if a live worker already hosts this actor
        # id, report it instead of spawning a duplicate incarnation.
        for w in self.workers.values():
            if w.actor_id == spec.actor_id and w.proc.poll() is None:
                return {"pid": w.pid}
        req = ResourceSet(spec.resources)
        bundle_key = None
        if isinstance(spec.scheduling_strategy, PlacementGroupScheduling):
            bundle_key = self._find_bundle(spec.scheduling_strategy, spec.resources)
            if bundle_key is None:
                raise RuntimeError("no bundle capacity for actor")
            self._bundle_pools[bundle_key].allocate(req)
        else:
            if not self.resources.can_fit(req):
                raise RuntimeError("insufficient resources for actor")
            self.resources.allocate(req)
        # Chip isolation is mandatory for TPU actors: failing the creation
        # (controller reschedules) beats spawning an unisolated process
        # that would grab every chip on the host.
        chips = None
        if spec.resources.get("TPU", 0) >= 1:
            chips = self._allocate_tpu_chips(int(spec.resources["TPU"]))
            if chips is None:
                if bundle_key is not None:
                    self._bundle_pools[bundle_key].release(req)
                else:
                    self.resources.release(req)
                raise RuntimeError("TPU chip ids unavailable (pool exhausted)")
        w = self._spawn_worker(actor_spec=spec, tpu_chips=chips)
        w.actor_resources = dict(spec.resources)
        w.actor_bundle_key = bundle_key
        return {"pid": w.pid}

    def _release_actor_resources(self, w: WorkerProc) -> None:
        self._free_tpu_chips(w.tpu_chips)
        w.tpu_chips = None
        if w.actor_resources is None:
            return
        req = ResourceSet(w.actor_resources)
        w.actor_resources = None
        if w.actor_bundle_key is not None:
            pool = self._bundle_pools.get(w.actor_bundle_key)
            if pool is not None:
                pool.release(req)
        else:
            self.resources.release(self._withhold_blocked_release(w, req))
        self._notify_capacity()

    async def d_kill_worker(self, payload, conn):
        actor_id = payload.get("actor_id")
        pid = payload.get("pid")
        for w in list(self.workers.values()):
            if (actor_id is not None and w.actor_id == actor_id) or (pid and w.pid == pid):
                try:
                    w.proc.kill()
                except Exception:
                    pass
                return True
        return False

    # ---- placement group bundles (2PC) --------------------------------
    async def d_prepare_bundle(self, payload, conn):
        if self._draining:
            raise RuntimeError("node is draining; cannot reserve bundles")
        key = (payload["pg_id"], payload["bundle_index"])
        req = ResourceSet(payload["resources"])
        if key in self._prepared_bundles or key in self._bundle_pools:
            return True
        if not self.resources.can_fit(req):
            raise RuntimeError("cannot reserve bundle: insufficient resources")
        self.resources.allocate(req)
        self._prepared_bundles[key] = payload["resources"]
        return True

    async def d_commit_bundle(self, payload, conn):
        key = (payload["pg_id"], payload["bundle_index"])
        resources = self._prepared_bundles.pop(key, None)
        if resources is None:
            if key in self._bundle_pools:
                return True
            raise RuntimeError("commit without prepare")
        self._bundle_pools[key] = NodeResources(ResourceSet(resources))
        self._notify_capacity()
        return True

    async def d_release_bundle(self, payload, conn):
        key = (payload["pg_id"], payload["bundle_index"])
        resources = self._prepared_bundles.pop(key, None)
        if resources is not None:
            self.resources.release(ResourceSet(resources))
        pool = self._bundle_pools.pop(key, None)
        if pool is not None:
            self.resources.release(pool.total)
        return True

    # ---- object store services ----------------------------------------
    async def d_adopt_object(self, payload, conn):
        self.store.adopt(ObjectID(payload["object_id"]), payload["size"])
        return True

    async def d_get_object_meta(self, payload, conn):
        meta = self.store.ensure_local(ObjectID(payload["object_id"]))
        if meta is None:
            return None
        return {"segment": meta[0], "size": meta[1]}

    async def d_pull_object(self, payload, conn):
        """Ensure the object is in the local store, pulling chunks from a
        source node. The heavy lifting — admission control, single-flight
        coalescing, resumable multi-source transfer, end-to-end integrity
        — lives in :class:`core.pull_manager.PullManager`. The caller may
        stamp ``deadline_s`` (its remaining budget) so the manager's
        retry/backoff loops are capped by the SAME deadline the caller
        enforces, instead of retrying into a dead wait."""
        from ray_tpu.core.deadline import deadline_scope

        object_id = ObjectID(payload["object_id"])
        with deadline_scope(payload.get("deadline_s")):
            return await self.pulls.pull(object_id, payload["sources"])

    #: above this size the first (uncached) digest computation would risk
    #: blowing the puller's fixed probe timeout — serve digest=None and
    #: warm the cache in the background instead (per-chunk crcs still
    #: protect the transfer; the whole-object gate kicks in once cached)
    _DIGEST_SYNC_MAX_BYTES = 1 << 30

    async def d_object_info(self, payload, conn):
        """Transfer head: size + whole-object crc32 digest (computed
        lazily off-loop, cached on the entry) — the end-to-end integrity
        token the puller verifies before sealing."""
        object_id = ObjectID(payload["object_id"])
        meta = self.store.ensure_local(object_id)
        if meta is None:
            return None
        loop = asyncio.get_event_loop()
        if meta[1] > self._DIGEST_SYNC_MAX_BYTES:
            digest = self.store.peek_digest(object_id)
            if digest is None:
                loop.run_in_executor(None, self.store.digest_of, object_id)
        else:
            digest = await loop.run_in_executor(
                None, self.store.digest_of, object_id
            )
        return {"size": meta[1], "digest": digest}

    async def d_fetch_chunk(self, payload, conn):
        """One transfer chunk. A receiver that stamps ``raw: True`` gets
        a RAW frame: the payload is written to the socket straight from
        this node's mapped segment (scatter-gather, no per-chunk bytes
        copy) with the crc riding the frame header; the receiver reads
        it directly into its destination segment and verifies there.
        Legacy receivers get the pickled ``(bytes, crc)`` tuple."""
        import zlib

        object_id = ObjectID(payload["object_id"])
        if payload.get("raw"):
            from ray_tpu.core.rpc import RawPayload

            win = self.store.read_window(
                object_id, payload["offset"], payload["length"]
            )
            if win is None:
                raise KeyError(f"object {object_id.hex()[:12]} not here")
            # crc over the mapped view — computed by the sender so a
            # corrupt wire byte (or segment) is caught receiver-side
            # before the chunk commits
            return RawPayload(win.view, meta=zlib.crc32(win.view), close=win.close)
        data = self.store.read_range(object_id, payload["offset"], payload["length"])
        if data is None:
            raise KeyError(f"object {object_id.hex()[:12]} not here")
        # per-chunk crc: the receiver verifies BEFORE the bytes commit to
        # its destination segment (a corrupt chunk is re-fetched, not
        # served)
        return (data, zlib.crc32(data))

    async def d_delete_object(self, payload, conn):
        """Delete an object. ``allow_recycle`` is sent by the deleting
        OWNER (segment creator): if no reader ever resolved the object
        here, the entry is dropped WITHOUT unlinking and True is returned
        — the caller renames the inode into its warm-page reuse pool."""
        return self.store.delete(
            ObjectID(payload["object_id"]),
            allow_recycle=bool(payload.get("allow_recycle")),
            # KV-migration importers send this after releasing their
            # mapping: the received segment's inode joins the store's
            # receive reuse pool instead of being unlinked
            recycle_receive=bool(payload.get("recycle_receive")),
        )

    # ---- cluster KV-tier registry (PR 17) ------------------------------
    def _kv_tier_drop_locked(self, digest: str) -> None:
        """Remove one tier entry and its store object (best-effort: the
        object may already be gone if a reader raced a delete)."""
        ent = self._kv_tier.pop(digest, None)
        if ent is None:
            return
        oid_hex = (ent.get("desc") or {}).get("object_id")
        if oid_hex:
            try:
                self.store.delete(ObjectID(bytes.fromhex(oid_hex)))
            except Exception:  # noqa: BLE001
                pass

    def _kv_tier_sweep(self) -> None:
        """TTL + cap eviction for tier entries (called from _reap_loop).
        The tier is a cache: entries nobody faulted in for kv_tier_ttl_s
        expire unconditionally; past kv_tier_max_entries the victim is
        chosen by POPULARITY — lowest hit count first, oldest recency
        among ties — not pure insertion age. A shared system-prompt
        prefix that every request faults in must outlive a parade of
        colder, newer one-off entries, or the cap turns the tier into a
        FIFO that evicts exactly its most valuable bytes."""
        now = time.monotonic()
        if now - self._last_kv_tier_sweep < 1.0:
            return
        self._last_kv_tier_sweep = now
        for digest in [
            d for d, ent in self._kv_tier.items() if now > ent["expiry"]
        ]:
            self._kv_tier_drop_locked(digest)
        cap = max(1, GLOBAL_CONFIG.kv_tier_max_entries)
        while len(self._kv_tier) > cap:
            victim, best = None, None
            # O(n) scan per eviction: the OrderedDict's order IS the
            # recency axis (get/put move_to_end), so position breaks
            # hit-count ties toward the longest-unused entry. Bounded
            # by the 1s sweep throttle + the entry cap.
            for i, (d, ent) in enumerate(self._kv_tier.items()):
                score = (ent.get("hits", 0), i)
                if best is None or score < best:
                    victim, best = d, score
            self._kv_tier_drop_locked(victim)

    async def d_kv_tier_put(self, payload, conn):
        """Register one tier entry (the object itself was already
        published + adopted through the normal store path — this call
        transfers LIFETIME ownership to the daemon's registry). A re-put
        of a live digest is a USE signal (some replica re-derived the
        same prefix): it bumps the hit count the sweep's popularity
        eviction keys on."""
        digest = str(payload["digest"])
        prev = self._kv_tier.get(digest)
        self._kv_tier[digest] = {
            "desc": payload["desc"],
            "expiry": time.monotonic() + GLOBAL_CONFIG.kv_tier_ttl_s,
            "hits": (prev["hits"] + 1) if prev else 0,
        }
        self._kv_tier.move_to_end(digest)
        self._kv_tier_sweep()
        return True

    async def d_kv_tier_get(self, payload, conn):
        """Lookup one entry; a hit refreshes TTL + recency and bumps the
        popularity count (a faulted-in prefix is by definition still
        hot — hit-weighted cap eviction keeps it past colder entries)."""
        ent = self._kv_tier.get(str(payload["digest"]))
        if ent is None:
            return None
        ent["expiry"] = time.monotonic() + GLOBAL_CONFIG.kv_tier_ttl_s
        ent["hits"] = ent.get("hits", 0) + 1
        self._kv_tier.move_to_end(str(payload["digest"]))
        return ent["desc"]

    async def d_kv_tier_del(self, payload, conn):
        self._kv_tier_drop_locked(str(payload["digest"]))
        return True

    async def d_kv_tier_list(self, payload, conn):
        """Full registry dump — the warm-restart recovery read: a
        replacement replica booting on this node re-adverts every
        surviving entry within one gossip beat."""
        return {
            "entries": {d: ent["desc"] for d, ent in self._kv_tier.items()}
        }

    def _peer(self, host: str, port: int) -> RpcClient:
        key = (host, port)
        client = self._peer_clients.get(key)
        if client is None:
            # peers of a daemon are other daemons (object transfer)
            client = self._peer_clients[key] = RpcClient(
                host, port, name=f"peer-{port}", role="noded"
            )
        return client

    # ---- controller fencing (epoch gate) -------------------------------
    def _note_controller_epoch(self, epoch: int) -> None:
        if epoch > self._controller_epoch_seen:
            if self._controller_epoch_seen:
                logger.info(
                    "controller epoch %d -> %d (restart/takeover)",
                    self._controller_epoch_seen, epoch,
                )
            self._controller_epoch_seen = epoch

    def _controller_epoch_gate(self, method: str, epoch: int):
        """RpcServer fencing gate (core/rpc.py meta slot 3): record the
        highest controller epoch seen; reject anything lower with a
        structured ``stale_controller`` error — the deposed controller
        takes it as the order to exit. Split-brain writes become a
        counted non-event instead of silent state corruption."""
        if epoch < self._controller_epoch_seen:
            from ray_tpu.observability.rpc_metrics import (
                CONTROLLER_FENCED_WRITES,
            )

            CONTROLLER_FENCED_WRITES.inc()
            logger.warning(
                "fenced stale controller write %s (epoch %d < %d)",
                method, epoch, self._controller_epoch_seen,
            )
            from ray_tpu.core.rpc import StaleControllerError

            return StaleControllerError(
                f"stale_controller: write {method!r} carries epoch {epoch} "
                f"but epoch {self._controller_epoch_seen} has taken over — "
                "the deposed controller must exit",
                seen_epoch=self._controller_epoch_seen,
            )
        self._note_controller_epoch(epoch)
        return None

    async def d_controller_hello(self, payload, conn):
        """A (new or resurrected) controller announces itself. A new
        incumbent's hello raises the fencing floor cluster-wide before
        it even binds the service port; a zombie's hello is exactly the
        write the epoch gate bounces (it never reaches this handler)."""
        return {"ok": True, "node_id": self.node_id.binary(),
                "epoch_seen": self._controller_epoch_seen}

    # ---- misc ----------------------------------------------------------
    async def d_ping(self, payload, conn):
        return "pong"

    async def d_hello(self, payload, conn):
        """Driver handshake: learn the local node id."""
        return {"node_id": self.node_id.binary()}

    async def d_list_objects(self, payload, conn):
        return self.store.list_entries()

    async def d_stats(self, payload, conn):
        return {
            "node_id": self.node_id.binary(),
            "store": self.store.stats(),
            "num_workers": len(self.workers),
            "num_idle": len(self.idle),
            "num_leases": len(self.leases),
            "resources": self.resources.to_dict(),
            "metrics_port": getattr(self, "metrics_port", 0),
        }

    async def d_event_stats(self, payload, conn):
        """Per-handler timing + loop liveness (reference event_stats.h
        debug dump) for this daemon process."""
        from ray_tpu.observability.event_stats import debug_snapshot

        return debug_snapshot()

    async def d_metrics_text(self, payload, conn):
        """This daemon's full Prometheus registry as exposition text —
        the controller's federation scrape (``c_cluster_telemetry``)
        aggregates every node's registry with ``node`` labels from here,
        so one scrape of the controller sees the whole cluster."""
        from ray_tpu.observability.metrics import render

        loop = asyncio.get_event_loop()
        # render() runs collect callbacks (store stats etc.) — keep the
        # lock-taking text assembly off the daemon's event loop
        return await loop.run_in_executor(None, render)
