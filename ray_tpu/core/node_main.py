"""Standalone node daemon process (worker nodes / simulated multi-node).

Reference: ``raylet/main.cc:123`` — boots a NodeManager against an
existing control plane. Used by the test ``Cluster`` fixture
(``cluster_utils.py``) to add nodes on one machine.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys


async def amain(args) -> None:
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.node_daemon import NodeDaemon

    if args.system_config:
        GLOBAL_CONFIG.apply_system_config(json.loads(args.system_config))
    host, cport = args.controller.rsplit(":", 1)
    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = args.num_cpus
    labels = json.loads(args.labels) if args.labels else {}
    daemon = NodeDaemon(
        host,
        int(cport),
        resources=resources or None,
        session_dir=args.session_dir,
        labels=labels,
    )
    dport = await daemon.start()
    print(json.dumps({"daemon_port": dport, "node_id": daemon.node_id.hex()}), flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    # SIGTERM is a preemption WARNING (spot reclaims / maintenance events
    # deliver it before the kill): enter the drain protocol — self-report
    # DRAINING, finish running work within the grace, replicate objects
    # off-node, deregister — then exit. The drain completes immediately
    # on an idle node, so routine teardown stays fast; an escalating
    # reaper's SIGKILL still bounds a slow drain. SIGINT stops abruptly.
    daemon.on_drained = stop.set
    if GLOBAL_CONFIG.drain_on_sigterm:
        loop.add_signal_handler(
            signal.SIGTERM,
            lambda: daemon.start_drain("SIGTERM (preemption warning)"),
        )
    else:
        loop.add_signal_handler(signal.SIGTERM, stop.set)
    loop.add_signal_handler(signal.SIGINT, stop.set)
    # see head_main: driver-owned nodes exit when their spawner dies
    from ray_tpu.util.reaper import start_orphan_watch

    start_orphan_watch(lambda: loop.call_soon_threadsafe(stop.set))
    await stop.wait()
    await daemon.stop()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--controller", type=str, required=True)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--resources", type=str, default="")
    parser.add_argument("--labels", type=str, default="")
    parser.add_argument("--session-dir", type=str, default=None)
    parser.add_argument("--system-config", type=str, default="")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
