"""Shared-memory object store (plasma equivalent).

Reference: ``src/ray/object_manager/plasma/`` — an immutable shm store
owned by the node daemon (``store.h:55``), LRU eviction
(``eviction_policy.h``), disk fallback/spilling
(``raylet/local_object_manager.h:110``), client attach by FD-passing.

TPU-native redesign: each object is one POSIX shm segment named after its
ObjectID, created and written *by the producing worker* (zero-copy create;
no FD passing needed — the name is the capability) then *adopted* by the
node daemon, which owns lifetime: capacity accounting, LRU spill-to-disk,
restore, delete. POSIX unlink semantics make eviction safe: readers that
already attached keep valid mappings; only the name disappears.

Three pieces:
  * ``ShmStore``     — daemon-side authority (runs inside the node daemon).
  * ``StoreClient``  — worker-side: create/write and attach/read segments.
  * ``MemoryStore``  — per-worker in-process store for small/inline objects
                       (reference ``CoreWorkerMemoryStore``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory, resource_tracker
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import ObjectID

logger = logging.getLogger(__name__)


def segment_name(object_id: ObjectID) -> str:
    return "rt-" + object_id.hex()


_tracker_prestarted = False


def ensure_scrubbed_tracker() -> None:
    """Pre-spawn multiprocessing's shm resource tracker with accelerator
    tunnel env triggers removed. The tracker is spawned lazily with the
    CURRENT process env on first SharedMemory use; on hosts where an env
    var makes sitecustomize start a per-process tunnel client, an
    unscrubbed tracker burns ~half a core forever (and may never even
    reach its serve loop). Idempotent; call before first shm touch."""
    global _tracker_prestarted
    if _tracker_prestarted:
        return
    _tracker_prestarted = True
    from ray_tpu.core.config import GLOBAL_CONFIG

    keys = [k for k in GLOBAL_CONFIG.strip_child_env.split(",") if k]
    saved = {k: os.environ.pop(k) for k in keys if k in os.environ}
    # (scrub_child_env stashes for descendants; here the var must be GONE
    # from the tracker's env entirely, so plain pop/restore is right.)
    try:
        resource_tracker.ensure_running()
    except Exception:
        pass
    finally:
        os.environ.update(saved)


def _attach(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment without the resource tracker claiming
    it (py3.12's tracker would unlink segments it never created when this
    process exits)."""
    try:
        return shared_memory.SharedMemory(name=name, create=False, track=False)  # py>=3.13
    except TypeError:
        seg = shared_memory.SharedMemory(name=name, create=False)
        try:
            resource_tracker.unregister(seg._name, "shared_memory")  # noqa: SLF001
        except Exception:
            pass
        return seg


# MADV_POPULATE_WRITE (Linux 5.14+; mmap module may predate the constant):
# pre-fault a fresh segment's pages in ONE syscall before the bulk copy.
# Per-page fault-on-write costs ~10× the copy itself on virtualized hosts
# (measured 0.6 vs 3.4+ GB/s on the bench box for 64 MiB puts).
_MADV_POPULATE_WRITE = getattr(__import__("mmap"), "MADV_POPULATE_WRITE", 23)


def _prefault(seg: shared_memory.SharedMemory) -> None:
    try:
        seg._mmap.madvise(_MADV_POPULATE_WRITE)  # noqa: SLF001
    except Exception:
        pass  # old kernel / unsupported — the copy still works, just slower


def _create(name: str, size: int) -> shared_memory.SharedMemory:
    try:
        seg = shared_memory.SharedMemory(name=name, create=True, size=size, track=False)
    except TypeError:
        seg = shared_memory.SharedMemory(name=name, create=True, size=size)
        try:
            resource_tracker.unregister(seg._name, "shared_memory")  # noqa: SLF001
        except Exception:
            pass
    if size >= (1 << 20):  # syscall not worth it for small segments
        _prefault(seg)
    return seg


class ObjectStoreFull(Exception):
    pass


class SegmentWindow:
    """A memoryview over (a range of) a store segment plus the attachment
    keeping it valid — the zero-copy unit of the data plane. Senders get
    read windows (``read_window``) and write them straight to the socket;
    receivers get the writable window into an UNSEALED entry
    (``receive_window``) and read chunk payloads directly into it.

    ``close()`` releases the view then the mapping; it tolerates live
    sub-views (a late in-flight receive still holding a slice) by leaving
    the mapping open — the process-lifetime leak of one mapping beats a
    BufferError masking a transfer result."""

    __slots__ = ("_seg", "view")

    def __init__(self, seg: shared_memory.SharedMemory, view: memoryview):
        self._seg = seg
        self.view = view

    def __len__(self) -> int:
        return len(self.view)

    def close(self) -> None:
        try:
            self.view.release()
            self._seg.close()
        except BufferError:
            logger.debug("segment window closed with live sub-views; mapping kept")
        except Exception:
            pass


@dataclass
class _Entry:
    size: int
    sealed: bool = True
    pinned: int = 0
    spilled_path: Optional[str] = None
    in_shm: bool = True
    created_at: float = field(default_factory=time.monotonic)
    # crc32 content digest, computed lazily on first object_info serve
    # (or recorded at seal time by the pull manager) — the end-to-end
    # integrity token carried with transfer metadata
    digest: Optional[int] = None
    # False when a streaming receive ATTACHED to a pre-existing inode
    # (simulated multi-node: the "remote" source shares this /dev/shm, so
    # the segment already exists with identical immutable content) — an
    # aborted receive must then NOT unlink it out from under the source
    inode_owner: bool = True
    # True once ANY reader resolved this object through the daemon
    # (get_object_meta / transfer). Gates segment recycling: an inode no
    # process ever attached can be renamed+rewritten by its creator with
    # warm pages; one that was read may back live zero-copy views.
    read_by_any: bool = False
    # True for copies CREATED on this node (worker put/task output via
    # adopt); False for transfer-received replicas (create_with_data).
    # The drain flush replicates only primaries — secondaries already
    # live elsewhere.
    primary: bool = True


class ShmStore:
    """Daemon-side store authority. Thread-safe; no asyncio dependency."""

    def __init__(self, capacity_bytes: Optional[int] = None, spill_dir: Optional[str] = None):
        ensure_scrubbed_tracker()
        self.capacity = capacity_bytes or GLOBAL_CONFIG.object_store_memory_bytes
        self.spill_dir = spill_dir or GLOBAL_CONFIG.object_spilling_dir or "/tmp/ray_tpu_spill"
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()  # LRU order
        self._used = 0
        self._lock = threading.RLock()
        self.num_spilled = 0
        self.num_restored = 0
        self.num_evicted = 0
        # worker reuse pools hold real tmpfs pages the entry table no
        # longer tracks; admission control reads their size from the
        # filesystem (the one source of truth that survives worker
        # death/shutdown), cached briefly
        self._pool_debt = 0
        self._pool_debt_ts = 0.0
        # daemon-side receive-segment reuse pool (KV-migration satellite):
        # transfer-received segments deleted with ``recycle_receive`` (and
        # aborted receives this store created) keep their warm inode here
        # — pool file name -> byte size, oldest first — and the next
        # allocate_receive of a fitting size RENAMES one back instead of
        # paying segment create + zero-fill (no MADV_POPULATE on this
        # kernel; warm pages are the substitute)
        self._recv_pool: "OrderedDict[str, int]" = OrderedDict()
        self._recv_pool_bytes = 0
        self._recv_pool_seq = 0
        self.num_recv_pool_hits = 0
        self.num_recv_pool_puts = 0

    # -- accounting ------------------------------------------------------
    @property
    def used_bytes(self) -> int:
        return self._used

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            # unsealed (mid-receive) entries are invisible to readers
            return e is not None and e.sealed

    def list_entries(self) -> List[Dict[str, object]]:
        """State-API view of every tracked object (``ray list objects``)."""
        with self._lock:
            return [
                {
                    "object_id": oid.hex(),
                    "size": e.size,
                    "in_shm": e.in_shm,
                    "pinned": e.pinned,
                    "spilled": e.spilled_path is not None,
                    "primary": e.primary,
                    "sealed": e.sealed,
                }
                for oid, e in self._entries.items()
            ]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_objects": len(self._entries),
                "num_spilled": self.num_spilled,
                "num_restored": self.num_restored,
                "num_evicted": self.num_evicted,
                "recv_pool_bytes": self._recv_pool_bytes,
                "recv_pool_segments": len(self._recv_pool),
                "recv_pool_hits": self.num_recv_pool_hits,
                "recv_pool_puts": self.num_recv_pool_puts,
            }

    # -- receive-segment reuse pool --------------------------------------
    def _pool_receive_segment_locked(self, object_id: ObjectID, size: int) -> bool:
        """Move a private receive segment's inode into the reuse pool
        instead of unlinking it. Caller must hold the lock and must have
        already dropped the entry. Returns False (caller unlinks) when
        pooling is off, full, or the rename fails."""
        limit = GLOBAL_CONFIG.receive_segment_pool_bytes
        if limit <= 0 or size <= 0:
            return False
        self._recv_pool_seq += 1
        pool_name = f"rt-rpool-{os.getpid()}-{self._recv_pool_seq}"
        try:
            os.rename(
                os.path.join(_SHM_DIR, segment_name(object_id)),
                os.path.join(_SHM_DIR, pool_name),
            )
        except OSError:
            return False
        try:
            # physical size, not the entry's logical size: a segment that
            # was itself a pool hit can be larger than the object it held
            size = os.path.getsize(os.path.join(_SHM_DIR, pool_name))
        except OSError:
            pass
        self._recv_pool[pool_name] = size
        self._recv_pool_bytes += size
        self.num_recv_pool_puts += 1
        while self._recv_pool_bytes > limit and self._recv_pool:
            victim, vsize = self._recv_pool.popitem(last=False)
            self._recv_pool_bytes -= vsize
            try:
                os.unlink(os.path.join(_SHM_DIR, victim))
            except OSError:
                pass
        return True

    def _take_recv_pooled_locked(self, object_id: ObjectID, size: int) -> bool:
        """Claim a pooled receive segment that fits ``size`` without
        gross waste (same tight-fit rule as the worker pool: slack is
        invisible to accounting, bound it) and rename it to the object's
        segment name. Never overwrites an existing inode — on simulated
        shared-/dev/shm clusters the target name may BE the source's
        live copy, and a rename-over would destroy it (the ``forget()``
        hazard class); the plain create path handles that case."""
        target = os.path.join(_SHM_DIR, segment_name(object_id))
        if os.path.exists(target):
            return False
        for name, psize in self._recv_pool.items():
            if psize >= size and psize <= size + max(size >> 3, 1 << 20):
                del self._recv_pool[name]
                self._recv_pool_bytes -= psize
                try:
                    os.rename(os.path.join(_SHM_DIR, name), target)
                except OSError:
                    try:
                        os.unlink(os.path.join(_SHM_DIR, name))
                    except OSError:
                        pass
                    return False
                self.num_recv_pool_hits += 1
                return True
        return False

    # -- create/adopt ----------------------------------------------------
    def adopt(self, object_id: ObjectID, size: int) -> None:
        """Take ownership of a worker-created, already-written segment."""
        with self._lock:
            if object_id in self._entries:
                return
            self._make_room(size)
            self._entries[object_id] = _Entry(size=size)
            self._used += size

    def create_with_data(self, object_id: ObjectID, data: memoryview) -> None:
        """Daemon-side create (object transfer receive path)."""
        size = len(data)
        with self._lock:
            if object_id in self._entries:
                return
            self._make_room(size)
            try:
                seg = _create(segment_name(object_id), size)
                seg.buf[:size] = data
                seg.close()
            except FileExistsError:
                # Simulated multi-node: the "remote" node shares this
                # machine's /dev/shm, so the segment already exists with
                # identical content (objects are immutable) — adopt as-is.
                pass
            self._entries[object_id] = _Entry(size=size, primary=False)
            self._used += size

    # -- streaming receive (pull manager) --------------------------------
    # The destination segment is allocated UP FRONT and chunks are
    # written directly into it (no whole-object heap buffer). The entry
    # exists unsealed for the duration — invisible to every reader path
    # (contains/ensure_local/read_*) — and is either sealed atomically
    # once the content digest verifies, or aborted without a trace.

    def begin_receive(self, object_id: ObjectID) -> bool:
        """Reserve an unsealed entry for an incoming transfer. Returns
        False if the object is already present (sealed) — the pull is a
        no-op. A stale unsealed entry (aborted transfer that lost the
        race to clean up) is replaced."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                if e.sealed:
                    return False
                self._abort_receive_locked(object_id, e)
            return True

    def allocate_receive(self, object_id: ObjectID, size: int) -> str:
        """Create the destination segment for a begin_receive'd transfer
        (separate from begin_receive so admission control can run between
        the reservation and the allocation). Returns the segment name;
        the caller attaches and writes chunks into it."""
        with self._lock:
            self._make_room(size)
            inode_owner = True
            if not self._take_recv_pooled_locked(object_id, size):
                try:
                    seg = _create(segment_name(object_id), size)
                    seg.close()
                except FileExistsError:
                    # simulated multi-node: the source shares this
                    # /dev/shm, the inode already holds the (immutable)
                    # content — write over it with identical bytes, but
                    # never unlink it on abort (the source still serves
                    # from it)
                    inode_owner = False
            self._entries[object_id] = _Entry(
                size=size, sealed=False, primary=False, inode_owner=inode_owner
            )
            self._used += size
            return segment_name(object_id)

    def seal_receive(self, object_id: ObjectID, digest: Optional[int] = None) -> None:
        """Atomically publish a fully-received, digest-verified object:
        only now do readers see it."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            e.sealed = True
            e.digest = digest

    def abort_receive(self, object_id: ObjectID) -> None:
        """Tear down a failed transfer: the uncommitted segment is
        dropped; readers never saw the entry."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.sealed:
                return  # sealed entries are never aborted
            self._abort_receive_locked(object_id, e)

    def _abort_receive_locked(self, object_id: ObjectID, e: _Entry) -> None:
        self._entries.pop(object_id, None)
        self._used -= e.size
        if e.inode_owner:
            # no reader ever saw an unsealed entry, so the inode is
            # private: recycle it into the receive pool (a failed
            # transfer's retry is exactly the repeat case the pool is
            # for); unlink only when pooling declines it
            if self._pool_receive_segment_locked(object_id, e.size):
                return
            try:
                seg = _attach(segment_name(object_id))
                seg.unlink()
                seg.close()
            except FileNotFoundError:
                pass

    def receive_window(self, object_id: ObjectID) -> SegmentWindow:
        """The writable window into an UNSEALED entry (an in-flight
        transfer's destination segment): the pull manager reads verified
        chunk payloads straight into it — zero intermediate copies. Only
        the receiving transfer may hold this window; every reader path
        still denies the object until ``seal_receive``. Raises KeyError
        when no unsealed entry exists (never exposes sealed objects as
        writable)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.sealed:
                raise KeyError(
                    f"no unsealed receive entry for {object_id.hex()[:12]}"
                )
            size = e.size
        seg = _attach(segment_name(object_id))
        return SegmentWindow(seg, memoryview(seg.buf)[:size])

    def peek_digest(self, object_id: ObjectID) -> Optional[int]:
        """Cached digest only — never computes (cheap probe-path check)."""
        with self._lock:
            e = self._entries.get(object_id)
            return None if e is None else e.digest

    def digest_of(self, object_id: ObjectID) -> Optional[int]:
        """crc32 content digest, computed lazily and cached on the entry
        (the transfer-metadata integrity token). None if absent."""
        import zlib

        meta = self.ensure_local(object_id)
        if meta is None:
            return None
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return None
            if e.digest is not None:
                return e.digest
        name, size = meta
        try:
            seg = _attach(name)
        except FileNotFoundError:
            return None  # raced a spill/delete; caller retries via ensure_local
        try:
            digest = zlib.crc32(seg.buf[:size])
        finally:
            seg.close()
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.digest = digest
        return digest

    def _recycle_pool_debt(self) -> int:
        """Bytes held by worker segment-reuse pools (``rt-pool-*`` files):
        real tmpfs usage invisible to the entry table."""
        now = time.monotonic()
        if now - self._pool_debt_ts > 1.0:
            import glob

            debt = 0
            for path in glob.glob("/dev/shm/rt-pool-*"):
                try:
                    debt += os.path.getsize(path)
                except OSError:
                    pass
            self._pool_debt = debt
            self._pool_debt_ts = now
        return self._pool_debt

    def _make_room(self, size: int) -> None:
        if size > self.capacity:
            raise ObjectStoreFull(
                f"object of {size} bytes exceeds store capacity {self.capacity}"
            )
        threshold = int(self.capacity * GLOBAL_CONFIG.object_spilling_threshold)
        debt = self._recycle_pool_debt()
        # the receive pool holds real tmpfs pages too — drain it before
        # spilling live objects (pool entries are pure cache)
        while (
            self._used + debt + self._recv_pool_bytes + size > threshold
            and self._recv_pool
        ):
            victim, vsize = self._recv_pool.popitem(last=False)
            self._recv_pool_bytes -= vsize
            try:
                os.unlink(os.path.join(_SHM_DIR, victim))
            except OSError:
                pass
        debt += self._recv_pool_bytes
        while self._used + debt + size > threshold and self._spill_one():
            pass
        if self._used + debt + size > self.capacity:
            raise ObjectStoreFull(
                f"store full: used={self._used}, pool_debt={debt}, "
                f"requested={size}, capacity={self.capacity} and nothing spillable"
            )

    def _spill_one(self) -> bool:
        """Spill the least-recently-used unpinned in-shm object to disk."""
        victim = None
        for oid, e in self._entries.items():
            if e.in_shm and e.pinned == 0 and e.sealed:
                victim = (oid, e)
                break
        if victim is None:
            return False
        oid, e = victim
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, segment_name(oid))
        try:
            seg = _attach(segment_name(oid))
        except FileNotFoundError:
            # segment vanished (daemon restart); drop the entry
            self._drop(oid)
            return True
        try:
            with open(path, "wb") as f:
                f.write(seg.buf)
            seg.unlink()
        finally:
            seg.close()
        e.in_shm = False
        e.spilled_path = path
        self._used -= e.size
        self.num_spilled += 1
        logger.debug("spilled %s (%d bytes) to %s", oid.hex()[:12], e.size, path)
        return True

    def ensure_local(self, object_id: ObjectID) -> Optional[Tuple[str, int]]:
        """Return (segment_name, size) if present, restoring from spill if
        needed; None if unknown."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                # unsealed = a transfer in flight: readers must never see
                # a partially-written segment
                return None
            self._entries.move_to_end(object_id)  # LRU touch
            e.read_by_any = True
            if not e.in_shm:
                self._restore(object_id, e)
            return segment_name(object_id), e.size

    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        self._make_room(e.size)
        seg = _create(segment_name(object_id), e.size)
        with open(e.spilled_path, "rb") as f:
            f.readinto(seg.buf)
        seg.close()
        e.in_shm = True
        self._used += e.size
        self.num_restored += 1

    def read_bytes(self, object_id: ObjectID) -> Optional[bytes]:
        """Copy out an object's bytes (transfer send path)."""
        meta = self.ensure_local(object_id)
        if meta is None:
            return None
        name, size = meta
        seg = _attach(name)
        try:
            return bytes(seg.buf[:size])
        finally:
            seg.close()

    def read_range(self, object_id: ObjectID, offset: int, length: int) -> Optional[bytes]:
        """Copy one chunk (transfer send path — avoids copying the whole
        object per chunk request)."""
        meta = self.ensure_local(object_id)
        if meta is None:
            return None
        name, size = meta
        seg = _attach(name)
        try:
            end = min(size, offset + length)
            return bytes(seg.buf[offset:end])
        finally:
            seg.close()

    def read_window(
        self, object_id: ObjectID, offset: int, length: int
    ) -> Optional[SegmentWindow]:
        """Zero-copy chunk view (transfer send path): the returned window
        is written to the socket straight from the mapped segment — no
        per-chunk ``bytes`` copy. The caller closes it once the transport
        has consumed the buffer (RawPayload's close hook). Restores from
        spill like :meth:`read_range`; None if unknown."""
        meta = self.ensure_local(object_id)
        if meta is None:
            return None
        name, size = meta
        try:
            seg = _attach(name)
        except FileNotFoundError:
            return None  # raced a spill/delete; caller retries
        end = min(size, offset + length)
        return SegmentWindow(seg, memoryview(seg.buf)[offset:end])

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e:
                e.pinned += 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e and e.pinned > 0:
                e.pinned -= 1

    def delete(
        self,
        object_id: ObjectID,
        allow_recycle: bool = False,
        recycle_receive: bool = False,
    ) -> bool:
        """Drop an object. With ``allow_recycle`` (sent by the deleting
        OWNER, who created the segment and keeps it mapped), a segment no
        reader ever resolved is released *without unlinking*: the caller
        takes ownership of the inode for its reuse pool. Returns True in
        exactly that case.

        ``recycle_receive`` is the DAEMON-side analogue for
        transfer-received objects (KV migration): the caller asserts it
        was the object's only consumer and has released its mapping, so
        the inode goes into this store's receive-segment reuse pool
        instead of being unlinked. The store can't verify the assertion
        — a caller that lies hands a still-mapped inode to a future
        transfer, which would scribble over the liar's view — so only
        transfer-private objects (like migration payloads) may use it.
        Restricted to in-shm, unpinned, inode-owning entries."""
        with self._lock:
            if recycle_receive:
                e = self._entries.get(object_id)
                if (
                    e is not None
                    and e.in_shm
                    and e.pinned == 0
                    and e.inode_owner
                    and e.spilled_path is None
                ):
                    self._entries.pop(object_id)
                    self._used -= e.size
                    if self._pool_receive_segment_locked(object_id, e.size):
                        return True
                    # pooling declined: fall through to a plain unlink
                    try:
                        seg = _attach(segment_name(object_id))
                        seg.unlink()
                        seg.close()
                    except FileNotFoundError:
                        pass
                    return False
            if allow_recycle:
                e = self._entries.get(object_id)
                if (
                    e is not None
                    and e.in_shm
                    and not e.read_by_any
                    and e.spilled_path is None
                    and e.pinned == 0
                ):
                    self._entries.pop(object_id)
                    self._used -= e.size
                    return True
            self._drop(object_id)
            return False

    def forget(self, object_id: ObjectID) -> None:
        """Drop the entry WITHOUT unlinking the segment. Drain handoff:
        once a peer holds the replica, this store must stop claiming the
        object — but on a simulated (shared-/dev/shm) cluster the peer's
        "copy" is the SAME inode, so unlinking here (shutdown would)
        destroys the replica too. A real preempted host dies seconds
        later and takes the unreferenced inode with it."""
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            if e.in_shm:
                self._used -= e.size
            if e.spilled_path:
                try:
                    os.remove(e.spilled_path)
                except OSError:
                    pass

    def _drop(self, object_id: ObjectID) -> None:
        e = self._entries.pop(object_id, None)
        if e is None:
            return
        if e.in_shm:
            self._used -= e.size
            try:
                seg = _attach(segment_name(object_id))
                seg.unlink()
                seg.close()
            except FileNotFoundError:
                pass
        if e.spilled_path:
            try:
                os.remove(e.spilled_path)
            except OSError:
                pass

    def shutdown(self) -> None:
        with self._lock:
            for oid in list(self._entries):
                self._drop(oid)
            for name in self._recv_pool:
                try:
                    os.unlink(os.path.join(_SHM_DIR, name))
                except OSError:
                    pass
            self._recv_pool.clear()
            self._recv_pool_bytes = 0


_SHM_DIR = "/dev/shm"


class StoreClient:
    """Worker-side shm access. Keeps attachments cached so zero-copy views
    (numpy arrays backed by shm) stay valid for the process lifetime.

    Segment recycling (the plasma-arena insight, ``plasma/store.h:55``):
    page faults on a fresh mmap cost ~10× the copy on virtualized hosts,
    so segments whose objects were freed *without ever being read by
    another process* (daemon-confirmed) are renamed into a small pool —
    same inode, warm PTEs — and the next put of a fitting size reuses
    them at memcpy speed."""

    def __init__(self):
        ensure_scrubbed_tracker()
        self._attached: Dict[ObjectID, shared_memory.SharedMemory] = {}
        self._created: Dict[ObjectID, shared_memory.SharedMemory] = {}
        # reuse pool: (current_file_name, still-mapped segment)
        self._pool: List[Tuple[str, shared_memory.SharedMemory]] = []
        self._pool_bytes = 0
        self._pool_seq = 0
        self._lock = threading.Lock()

    def has_created(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._created

    def recycle(self, object_id: ObjectID) -> None:
        """Owner freed the object and the daemon confirmed no reader ever
        resolved it: keep the (still warm) segment for reuse. Caller owns
        the inode now — rename it out of the object namespace."""
        with self._lock:
            seg = self._created.pop(object_id, None)
            self._attached.pop(object_id, None)
            if seg is None:
                return
            limit = min(
                GLOBAL_CONFIG.object_store_recycle_bytes,
                GLOBAL_CONFIG.object_store_memory_bytes // 4,
            )
            size = seg.size
            if size < (1 << 20) or self._pool_bytes + size > limit:
                # Reject: unlink by the object's CURRENT file name —
                # seg.unlink() would use the original creation name, which
                # is stale for pool-reused segments (leak, or worse:
                # unlinking a re-produced object's live segment).
                try:
                    os.unlink(os.path.join(_SHM_DIR, segment_name(object_id)))
                except OSError:
                    pass
                try:
                    seg.close()
                except Exception:
                    pass
                return
            self._pool_seq += 1
            pool_name = f"rt-pool-{os.getpid()}-{self._pool_seq}"
            try:
                # NOTE: the file is named after the OBJECT (rename on reuse
                # keeps segment_name(oid) current); seg.name still holds
                # the segment's original creation name — don't use it.
                os.rename(
                    os.path.join(_SHM_DIR, segment_name(object_id)),
                    os.path.join(_SHM_DIR, pool_name),
                )
            except OSError:
                try:
                    seg.close()
                except Exception:
                    pass
                return
            self._pool.append((pool_name, seg))
            self._pool_bytes += size

    def _take_pooled(
        self, object_id: ObjectID, size: int
    ) -> Optional[shared_memory.SharedMemory]:
        """Claim a pooled segment that fits (without gross waste) and
        rename it to the new object's name. Same inode → warm pages."""
        with self._lock:
            for i, (name, seg) in enumerate(self._pool):
                # Tight fit only: physical slack beyond the logical size is
                # invisible to the daemon's accounting (entries record the
                # logical size), so bound it at 12.5% / 1 MiB.
                if seg.size >= size and seg.size <= size + max(size >> 3, 1 << 20):
                    del self._pool[i]
                    self._pool_bytes -= seg.size
                    try:
                        os.rename(
                            os.path.join(_SHM_DIR, name),
                            os.path.join(_SHM_DIR, segment_name(object_id)),
                        )
                    except OSError:
                        try:
                            seg.close()
                        except Exception:
                            pass
                        return None
                    return seg
        return None

    def create_and_write(self, object_id: ObjectID, ser) -> int:
        """Write a SerializedValue into a fresh segment; returns size.

        Serialized bytes go straight into the mapped segment (one copy) —
        the put-GB/s hot path."""
        size = ser.total_bytes
        seg = self._take_pooled(object_id, size)
        if seg is not None:
            ser.write_into_view(memoryview(seg.buf))
            with self._lock:
                stale = [
                    s
                    for s in (
                        self._created.pop(object_id, None),
                        self._attached.pop(object_id, None),
                    )
                    if s is not None and s is not seg
                ]
                self._created[object_id] = seg
            for s in stale:
                try:
                    s.close()
                except Exception:
                    pass
            return size
        try:
            seg = _create(segment_name(object_id), size)
        except FileExistsError:
            # Same object re-produced (task retry / simulated multi-node).
            # Re-serialization (cloudpickle) is not guaranteed byte-identical:
            # if the new payload is larger than the old segment, unlink and
            # recreate — POSIX unlink keeps existing readers' mappings valid.
            seg = _attach(segment_name(object_id))
            if len(seg.buf) < size:
                try:
                    seg.unlink()
                finally:
                    seg.close()
                seg = _create(segment_name(object_id), size)
        ser.write_into_view(memoryview(seg.buf))
        with self._lock:
            # Drop stale cached mappings (both caches): after a re-produce
            # the old unlinked inode must not win future read()s.
            stale = [
                s
                for s in (
                    self._created.pop(object_id, None),
                    self._attached.pop(object_id, None),
                )
                if s is not None and s is not seg
            ]
            self._created[object_id] = seg
        for s in stale:
            try:
                s.close()
            except Exception:
                pass
        return size

    def read(self, object_id: ObjectID, size: int) -> memoryview:
        with self._lock:
            seg = self._attached.get(object_id) or self._created.get(object_id)
            if seg is None:
                seg = _attach(segment_name(object_id))
                self._attached[object_id] = seg
        return memoryview(seg.buf)[:size]

    def release(self, object_id: ObjectID) -> None:
        with self._lock:
            seg = self._attached.pop(object_id, None) or self._created.pop(object_id, None)
        if seg is not None:
            try:
                seg.close()
            except Exception:
                pass

    def close_all(self) -> None:
        with self._lock:
            segs = list(self._attached.values()) + list(self._created.values())
            pool = self._pool
            self._attached.clear()
            self._created.clear()
            self._pool = []
            self._pool_bytes = 0
        for seg in segs:
            try:
                seg.close()
            except Exception:
                pass
        for name, seg in pool:
            try:
                os.unlink(os.path.join(_SHM_DIR, name))
            except OSError:
                pass
            try:
                seg.close()
            except Exception:
                pass


class MemoryStore:
    """In-process store for small objects; supports blocking waits.

    Reference: ``core_worker/store_provider/memory_store/``."""

    def __init__(self):
        self._data: Dict[ObjectID, bytes] = {}
        self._events: Dict[ObjectID, threading.Event] = {}
        self._lock = threading.Lock()

    def put(self, object_id: ObjectID, data: bytes) -> None:
        with self._lock:
            self._data[object_id] = data
            ev = self._events.pop(object_id, None)
        if ev:
            ev.set()

    # Reads are lock-free: dict.get on a key is atomic under the GIL and
    # this store is the owner-side INLINE CACHE — every get() on a small
    # task result goes through here, so a lock acquire per read is pure
    # hot-path overhead. Mutation (put/delete) stays locked for the
    # event bookkeeping.
    def get(self, object_id: ObjectID) -> Optional[bytes]:
        return self._data.get(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._data

    def wait_for(self, object_id: ObjectID, timeout: Optional[float]) -> Optional[bytes]:
        with self._lock:
            if object_id in self._data:
                return self._data[object_id]
            ev = self._events.get(object_id)
            if ev is None:
                ev = self._events[object_id] = threading.Event()
        if not ev.wait(timeout):
            return None
        with self._lock:
            return self._data.get(object_id)

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._data.pop(object_id, None)
