"""Ownership-based distributed memory management.

Reference: ``core_worker/reference_count.h:64`` — every object has exactly
one *owner*: the worker that created it (``put``) or submitted its
producing task. The owner holds the authoritative state machine

    PENDING → AVAILABLE(inline bytes | shm locations) | FAILED(error)
                      ↓
                    FREED

and the reference count split into local refs (ObjectRefs in the owner
process), *borrowers* (other processes that deserialized a ref), and
submitted-task references (the ref is an argument of an in-flight task).
When all three hit zero the object is freed: inline bytes dropped, every
node holding a shm copy told to delete. The producing ``TaskSpec`` is
retained while the object or any downstream dependent lives
(lineage pinning, ``reference_count.h:70-117``) so lost objects can be
reconstructed by resubmission (``object_recovery_manager.h:90``).
"""

from __future__ import annotations

import enum
import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ray_tpu.core.ids import ObjectID

logger = logging.getLogger(__name__)


class ObjState(enum.Enum):
    PENDING = 0
    AVAILABLE = 1
    FAILED = 2
    FREED = 3


@dataclass
class OwnedObject:
    state: ObjState = ObjState.PENDING
    inline: Optional[bytes] = None  # serialized value, for small objects
    locations: Set[bytes] = field(default_factory=set)  # node ids with a shm copy
    error: Optional[Exception] = None
    local_refs: int = 0
    borrowers: int = 0
    submitted: int = 0
    # refs contained in this object's value: kept alive while this lives
    contained: List[Any] = field(default_factory=list)
    lineage: Optional[Any] = None  # producing TaskSpec (reconstruction)
    waiters: List[threading.Event] = field(default_factory=list)
    #: one-shot callbacks fired (then dropped) on the next completion —
    #: the event-driven wait() path (``raylet/wait_manager.h:25``)
    ready_callbacks: List[Callable[[], None]] = field(default_factory=list)
    # lineage reconstruction bookkeeping (``object_recovery_manager.h:90``)
    recovering: bool = False
    reconstructions_left: int = -1  # -1 = not yet initialized from config

    def ready(self) -> bool:
        return self.state in (ObjState.AVAILABLE, ObjState.FAILED)

    def refcount(self) -> int:
        return self.local_refs + self.borrowers + self.submitted


class ReferenceCounter:
    """Owner-side object table. Thread-safe (sync API + io thread)."""

    def __init__(self, on_free: Callable[[ObjectID, OwnedObject], None]):
        self._objects: Dict[ObjectID, OwnedObject] = {}
        self._lock = threading.RLock()
        self._on_free = on_free

    # -- creation --------------------------------------------------------
    # ``hold=True`` creates the entry with one synthetic local ref (the
    # "submission hold"): the API layer releases it once real ObjectRefs
    # exist, so a completion racing ref-construction can't free the object,
    # while fire-and-forget objects (refs dropped while PENDING) are freed
    # as soon as their result lands.
    def create_pending(self, object_id: ObjectID, lineage=None, hold: bool = False) -> None:
        with self._lock:
            if object_id not in self._objects:
                self._objects[object_id] = OwnedObject(
                    lineage=lineage, local_refs=1 if hold else 0
                )

    def create_inline(self, object_id: ObjectID, data: bytes, contained=None, hold: bool = False) -> None:
        self._complete(
            object_id,
            lambda obj: (
                setattr(obj, "state", ObjState.AVAILABLE),
                setattr(obj, "inline", data),
                setattr(obj, "contained", list(contained or [])),
            ),
            hold=hold,
        )

    def create_at_location(self, object_id: ObjectID, node_id, contained=None, hold: bool = False) -> None:
        def mutate(obj):
            obj.state = ObjState.AVAILABLE
            obj.locations.add(node_id)
            obj.contained = list(contained or [])

        self._complete(object_id, mutate, hold=hold)

    def _complete(self, object_id: ObjectID, mutate, hold: bool = False) -> None:
        free_obj = None
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                obj = self._objects[object_id] = OwnedObject(local_refs=1 if hold else 0)
            elif obj.state == ObjState.AVAILABLE and not obj.recovering:
                # Objects are immutable: first completion wins. A late
                # duplicate reply — or a recovery resubmission whose spec
                # shares returns with a sibling that was never lost —
                # must not overwrite (or fail) a healthy value.
                return
            mutate(obj)
            obj.recovering = False  # any completion ends a reconstruction
            self._wake(obj)
            if obj.refcount() == 0:
                free_obj = self._objects.pop(object_id)
        if free_obj is not None:
            free_obj.state = ObjState.FREED
            try:
                self._on_free(object_id, free_obj)
            except Exception:
                logger.exception("free callback failed for %s", object_id.hex()[:12])

    # -- completion (task results) --------------------------------------
    def mark_available_inline(self, object_id: ObjectID, data: bytes) -> None:
        self.create_inline(object_id, data)

    def mark_available_at(self, object_id: ObjectID, node_id) -> None:
        self.create_at_location(object_id, node_id)

    def mark_failed(self, object_id: ObjectID, error: Exception) -> None:
        def mutate(obj):
            obj.state = ObjState.FAILED
            obj.error = error

        self._complete(object_id, mutate)

    def _wake(self, obj: OwnedObject) -> None:
        for ev in obj.waiters:
            ev.set()
        obj.waiters.clear()
        for cb in obj.ready_callbacks:
            try:
                cb()
            except Exception:
                logger.exception("ready callback failed")
        obj.ready_callbacks.clear()

    # -- queries ---------------------------------------------------------
    def get(self, object_id: ObjectID) -> Optional[OwnedObject]:
        with self._lock:
            return self._objects.get(object_id)

    def owns(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> Optional[OwnedObject]:
        """Block until the object completes (owner-side get path)."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                return None
            if obj.ready():
                return obj
            ev = threading.Event()
            obj.waiters.append(ev)
        if not ev.wait(timeout):
            # timed-out waiter must deregister: polling get(timeout=...)
            # loops on a slow object would otherwise grow waiters without
            # bound (completion is the only other drain)
            with self._lock:
                obj = self._objects.get(object_id)
                if obj is not None:
                    try:
                        obj.waiters.remove(ev)
                    except ValueError:
                        pass
            return None
        with self._lock:
            return self._objects.get(object_id)

    def on_ready(self, object_id: ObjectID, callback: Callable[[], None]) -> bool:
        """Register a one-shot completion callback. Returns True if the
        object is ALREADY ready (or unknown/freed — the waiter should
        treat that as ready and let get() surface the error); in that
        case the callback is NOT registered."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None or obj.ready():
                return True
            obj.ready_callbacks.append(callback)
            return False

    def remove_ready_callback(self, object_id: ObjectID, callback: Callable[[], None]) -> None:
        """Deregister a callback whose waiter gave up (timed-out wait) —
        otherwise repeated waits on a slow object accumulate closures."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                try:
                    obj.ready_callbacks.remove(callback)
                except ValueError:
                    pass

    def add_location(self, object_id: ObjectID, node_id: bytes) -> None:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                obj.locations.add(node_id)

    def remove_location(self, object_id: ObjectID, node_id: bytes) -> bool:
        """Node lost a copy. Returns True if the object now has no value
        anywhere (candidate for lineage reconstruction)."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                return False
            obj.locations.discard(node_id)
            return obj.state == ObjState.AVAILABLE and not obj.locations and obj.inline is None

    def begin_reconstruction(
        self,
        object_id: ObjectID,
        max_attempts: int,
        observed_locations: Optional[List] = None,
    ) -> Tuple[str, Optional[Any], Dict[ObjectID, List]]:
        """Try to start lineage reconstruction of a lost object.

        Returns ``(state, spec, stale_locations)``:
        ``("started", spec, stale)`` — caller must resubmit ``spec``;
        every *non-inline* return of the spec was reset to PENDING and
        its previously-tracked locations are in ``stale`` (caller should
        best-effort delete those copies: a transiently-unreachable node
        may still hold one, which would otherwise leak — and diverge if
        the task is nondeterministic).
        ``("pending", None, {})`` — a reconstruction is already in
        flight, just wait. ``("no", None, {})`` — can't recover (no
        lineage, attempts exhausted, or object gone/failed).
        """
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None or obj.lineage is None:
                return ("no", None, {})
            if obj.recovering or obj.state == ObjState.PENDING:
                return ("pending", None, {})
            if obj.state != ObjState.AVAILABLE:
                return ("no", None, {})
            if obj.inline is not None:
                # inline values live in the owner's memory and cannot be
                # lost to node death/drain — never burn a reconstruction
                # attempt on one (treat as always-available)
                return ("no", None, {})
            if observed_locations is not None and (
                obj.locations - {tuple(l) for l in observed_locations}
            ):
                # A location the failed fetch never tried exists (e.g. a
                # recovery completed in between): don't destroy it — the
                # caller should simply re-fetch.
                return ("pending", None, {})
            if obj.reconstructions_left < 0:
                obj.reconstructions_left = max_attempts
            if obj.reconstructions_left == 0:
                return ("no", None, {})
            spec = obj.lineage
            stale: Dict[ObjectID, List] = {}
            # Reset the shm-resident returns of the producing task (the
            # resubmission regenerates them). Inline returns live in the
            # owner's memory and cannot be lost — leave them untouched.
            for ret in getattr(spec, "return_ids", [object_id]):
                ret_obj = self._objects.get(ret)
                if ret_obj is None or ret_obj.inline is not None:
                    continue
                stale[ret] = list(ret_obj.locations)
                ret_obj.state = ObjState.PENDING
                ret_obj.locations.clear()
                ret_obj.error = None
                ret_obj.recovering = True
                if ret_obj.reconstructions_left < 0:
                    ret_obj.reconstructions_left = max_attempts
                ret_obj.reconstructions_left = max(
                    0, ret_obj.reconstructions_left - 1
                )
            return ("started", spec, stale)

    # -- refcounting -----------------------------------------------------
    def add_local(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                obj.local_refs += 1

    def remove_local(self, object_id: ObjectID) -> None:
        self._dec(object_id, "local_refs")

    def add_borrower(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                obj.borrowers += 1

    def remove_borrower(self, object_id: ObjectID) -> None:
        self._dec(object_id, "borrowers")

    def add_submitted(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                obj.submitted += 1

    def remove_submitted(self, object_id: ObjectID) -> None:
        self._dec(object_id, "submitted")

    def _dec(self, object_id: ObjectID, attr: str) -> None:
        free_obj = None
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                return
            setattr(obj, attr, max(0, getattr(obj, attr) - 1))
            if obj.refcount() == 0 and obj.ready():
                free_obj = self._objects.pop(object_id)
                free_obj.state = ObjState.FREED
        if free_obj is not None:
            try:
                self._on_free(object_id, free_obj)
            except Exception:
                logger.exception("free callback failed for %s", object_id.hex()[:12])

    def force_free(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
        if obj is not None:
            obj.state = ObjState.FREED
            self._on_free(object_id, obj)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_owned": len(self._objects),
                "num_pending": sum(1 for o in self._objects.values() if o.state == ObjState.PENDING),
            }
