"""Fault-tolerant pull manager: the daemon↔daemon object transfer path.

Reference: ``src/ray/object_manager/pull_manager.h`` — the reference
treats node-to-node transfer as a first-class fault domain: admission
control over in-flight pull bytes, chunked pipelining, retry on source
loss. This module is that subsystem for the shm store:

* **Zero-copy streaming shm writes** — the destination segment is
  allocated up front and RAW chunk replies (core/rpc.py kind 5) are
  received DIRECTLY into its writable window (no whole-object heap
  buffer, no per-chunk intermediate ``bytes``); the running crc folds
  over the received view. The store entry stays UNSEALED for the
  duration: readers (``contains``/``ensure_local``/``read_*``) never
  see a partial object; a failed transfer aborts the uncommitted
  segment.
* **Resumable multi-source transfer** — per-chunk timeout/retry with
  jittered backoff capped by the ambient ``core/deadline``; when a
  source dies or drains mid-pull the transfer fails over to the next
  source and RESUMES from the last verified offset — a lost source
  costs one chunk, not the object.
* **End-to-end integrity** — every chunk carries a crc32 computed by
  the sender and is verified before it COMMITS (a RAW payload occupies
  its reader-invisible destination range while the crc is checked in
  place; mismatch → re-fetch into the same range); the whole-object
  digest carried with ``object_info`` is verified before seal. A
  corrupt or truncated chunk can never be served to a reader.
* **Admission control + single-flight** — a bounded in-flight-bytes
  budget (``pull_max_inflight_bytes``) with strict FIFO queueing, so N
  concurrent pulls backpressure instead of OOMing the daemon; an object
  larger than the whole budget is admitted when alone. Concurrent pulls
  of the same object coalesce onto one transfer.
* **Data-plane chaos** — a seeded fault plan
  (``testing_pull_chaos``/``_seed``, :class:`util.chaos.DataFaultPlan`)
  consulted once per chunk attempt, receiver-side, so the whole fault
  schedule replays from one logged seed. Modes: chunk_drop /
  chunk_corrupt / chunk_stall / source_die_mid_transfer.

Results are structured: success is ``{"segment", "size"}`` (the shape
``get_object_meta`` returns); failure is ``{"failed": True,
"no_source": bool, "causes": {"host:port": reason}}`` so the owner can
distinguish "no source has it" (consult the relocation directory) from
"every transfer failed" (lineage reconstruction) — and log it once.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
import zlib
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.deadline import effective_timeout
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ShmStore
from ray_tpu.core.rpc import ConnectionLost
from ray_tpu.core.transport_retry import backoff_sleep
from ray_tpu.observability import tracing as _tracing
from ray_tpu.util.crc import crc32_combine

logger = logging.getLogger(__name__)


def _observe_pull_stage(stage: str, seconds: float) -> None:
    from ray_tpu.observability.rpc_metrics import PULL_STAGE_SECONDS

    PULL_STAGE_SECONDS.observe(seconds, labels={"stage": stage})

_Source = Tuple[str, int]


# ---------------------------------------------------------------------------
# seeded data-plane fault plan (same lazy-activation contract as
# rpc.active_fault_plan: built once per (spec, seed), seed logged so a
# failure reproduces from the log alone — util/chaos.py::SeededPlanCache)

_PLAN_CACHE = None
_PLAN_CACHE_LOCK = threading.Lock()


def active_pull_fault_plan():
    global _PLAN_CACHE
    if _PLAN_CACHE is None:
        from ray_tpu.util.chaos import DataFaultPlan, SeededPlanCache

        with _PLAN_CACHE_LOCK:
            if _PLAN_CACHE is None:
                _PLAN_CACHE = SeededPlanCache(
                    DataFaultPlan, "pull",
                    "testing_pull_chaos", "testing_pull_chaos_seed", logger,
                )
    return _PLAN_CACHE.active()


def _count_injection(mode: str) -> None:
    from ray_tpu.observability.rpc_metrics import RPC_CHAOS_INJECTIONS

    RPC_CHAOS_INJECTIONS.inc(labels={"mode": mode})


class _SourceFailed(Exception):
    """The current source is done for (died, drained, lost the object,
    or exhausted its chunk-retry budget): fail over to the next one.
    Carries the verified progress (offset, crc) at failure time so the
    caller RESUMES there — losing a source must cost one chunk, not the
    transfer."""

    def __init__(self, msg: str, offset: int = 0, crc: int = 0):
        super().__init__(msg)
        self.offset = offset
        self.crc = crc


class _ChunkIntegrityError(Exception):
    """Received chunk failed its crc/length check — re-fetch it."""


class _ChaosChunkError(Exception):
    """Injected chunk_drop fault (retry path, reason='chaos')."""


class _PullAbort(Exception):
    """The whole pull is over (deadline exhausted / every source
    failed): surface the structured failure. ``deadline=True`` marks
    budget exhaustion — the owner maps it to a TIMEOUT, not object
    loss, and coalesced waiters with their own budget re-initiate."""

    def __init__(self, msg: str, deadline: bool = False):
        super().__init__(msg)
        self.deadline = deadline


def _addr(src: _Source) -> str:
    return f"{src[0]}:{src[1]}"


class PullManager:
    """One per node daemon. All methods run on the daemon's event loop;
    the store itself is thread-safe."""

    def __init__(self, store: ShmStore, peer_factory):
        self.store = store
        self._peer = peer_factory  # (host, port) -> RpcClient (cached)
        self._inflight: Dict[ObjectID, asyncio.Future] = {}
        self._inflight_bytes = 0
        self._queued_bytes = 0
        self._admit_q: Deque[Tuple[int, asyncio.Future]] = deque()
        #: high-water mark of admitted bytes (admission-control tests)
        self.max_inflight_bytes_observed = 0

    # -- public entry ----------------------------------------------------
    async def pull(self, object_id: ObjectID, sources) -> Dict[str, object]:
        from ray_tpu.core.deadline import current_deadline

        while True:
            meta = self.store.ensure_local(object_id)
            if meta is not None:
                return {"segment": meta[0], "size": meta[1]}
            existing = self._inflight.get(object_id)
            if existing is None:
                break
            # single-flight: ride the in-progress transfer
            from ray_tpu.observability.rpc_metrics import PULL_COALESCED

            PULL_COALESCED.inc()
            result = await asyncio.shield(existing)
            if not (
                isinstance(result, dict)
                and result.get("failed")
                and result.get("deadline")
            ):
                return result
            # the shared transfer died on the INITIATOR's budget, not
            # ours — if this caller still has budget, run its own pull
            # (loop: re-check local state / any newer in-flight transfer)
            ambient = current_deadline()
            if ambient is not None and ambient.remaining() <= 0:
                return result
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._inflight[object_id] = fut
        result = None
        t0 = time.monotonic()
        try:
            try:
                # the span records only when the pull RPC carried a
                # sampled trace (rpc._dispatch re-entered it); the stage
                # histogram always observes
                with _tracing.span(
                    f"pull::{object_id.hex()[:12]}", "data"
                ):
                    result = await self._pull(object_id, sources)
            except Exception as e:  # noqa: BLE001 — waiters need a result
                logger.exception("pull of %s crashed", object_id.hex()[:12])
                result = {
                    "failed": True,
                    "no_source": False,
                    "causes": {"internal": repr(e)},
                }
        finally:
            _observe_pull_stage("total", time.monotonic() - t0)
            # resolve waiters even if the runner was CANCELLED (daemon
            # stopping) — coalesced pulls must never park forever
            self._inflight.pop(object_id, None)
            if not fut.done():
                fut.set_result(
                    result
                    if result is not None
                    else {
                        "failed": True,
                        "no_source": False,
                        "causes": {"internal": "pull cancelled"},
                    }
                )
        return result

    # -- admission control (FIFO, bounded in-flight bytes) ---------------
    def _set_gauges(self) -> None:
        from ray_tpu.observability.rpc_metrics import (
            PULL_INFLIGHT_BYTES,
            PULL_QUEUED_BYTES,
        )

        PULL_INFLIGHT_BYTES.set(self._inflight_bytes)
        PULL_QUEUED_BYTES.set(self._queued_bytes)
        if self._inflight_bytes > self.max_inflight_bytes_observed:
            self.max_inflight_bytes_observed = self._inflight_bytes

    async def _admit(self, size: int) -> None:
        budget = GLOBAL_CONFIG.pull_max_inflight_bytes
        if budget <= 0:
            self._inflight_bytes += size
            self._set_gauges()
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._admit_q.append((size, fut))
        self._queued_bytes += size
        self._set_gauges()
        self._pump_admission()
        try:
            await fut
        except asyncio.CancelledError:
            # cancelled in the instant AFTER admission granted: give the
            # bytes back — nobody else will
            if fut.done() and not fut.cancelled():
                self._release(size)
            raise
        finally:
            self._queued_bytes -= size
            self._set_gauges()

    def _pump_admission(self) -> None:
        budget = GLOBAL_CONFIG.pull_max_inflight_bytes
        while self._admit_q:
            size, fut = self._admit_q[0]
            if fut.cancelled():
                self._admit_q.popleft()
                continue
            # strict FIFO: the head parks the whole queue until it fits
            # (no small-pull overtaking — starvation-free by design);
            # an oversized object is admitted when nothing is in flight
            if self._inflight_bytes > 0 and self._inflight_bytes + size > budget:
                break
            self._admit_q.popleft()
            self._inflight_bytes += size
            fut.set_result(None)
        self._set_gauges()

    def _release(self, size: int) -> None:
        self._inflight_bytes -= size
        self._pump_admission()

    # -- source probing --------------------------------------------------
    async def _probe(
        self,
        candidates: Deque[_Source],
        object_id: ObjectID,
        causes: Dict[str, str],
    ):
        """Pop candidates until one serves the transfer head
        (``object_info``); record a cause per source that can't."""
        while candidates:
            src = candidates.popleft()
            timeout = effective_timeout(10.0)
            if timeout is not None and timeout <= 0:
                # budget gone ≠ sources gone: this must surface as a
                # TIMEOUT, never as "no source holds the object"
                raise _PullAbort("deadline exhausted before probe", deadline=True)
            try:
                head = await self._peer(src[0], src[1]).call(
                    "object_info",
                    {"object_id": object_id.binary()},
                    timeout=timeout,
                )
            except Exception as e:  # noqa: BLE001 — a dead source is a cause
                causes[_addr(src)] = f"probe failed: {e!r}"
                continue
            if head is None:
                causes[_addr(src)] = "object not found"
                continue
            return src, head
        return None, None

    async def _next_source(
        self,
        candidates: Deque[_Source],
        object_id: ObjectID,
        causes: Dict[str, str],
        size: int,
        digest: Optional[int],
    ) -> Optional[_Source]:
        """Failover: next candidate whose transfer head MATCHES the one
        this transfer started from (an object is immutable, so a size or
        digest disagreement marks a source corrupt, not the object new)."""
        while True:
            src, head = await self._probe(candidates, object_id, causes)
            if src is None:
                return None
            if head["size"] != size or (
                digest is not None
                and head.get("digest") is not None
                and head["digest"] != digest
            ):
                causes[_addr(src)] = (
                    f"transfer metadata mismatch (size {head['size']} != {size})"
                )
                continue
            return src

    # -- the transfer ----------------------------------------------------
    async def _pull(self, object_id: ObjectID, sources) -> Dict[str, object]:
        from ray_tpu.observability.rpc_metrics import (
            PULL_FAILURES,
            PULL_INTEGRITY_FAILURES,
            PULL_RESUMES,
        )

        plan = active_pull_fault_plan()
        causes: Dict[str, str] = {}
        candidates: Deque[_Source] = deque(
            dict.fromkeys(tuple(s) for s in sources)
        )
        probe_t0 = time.monotonic()
        try:
            src, head = await self._probe(candidates, object_id, causes)
            _observe_pull_stage("probe", time.monotonic() - probe_t0)
        except _PullAbort as e:
            PULL_FAILURES.inc()
            causes.setdefault("deadline" if e.deadline else "abort", str(e))
            logger.warning(
                "pull of %s aborted: %s (causes: %s)",
                object_id.hex()[:12], e, causes,
            )
            return {
                "failed": True,
                "no_source": False,
                "deadline": e.deadline,
                "causes": causes,
            }
        if src is None:
            PULL_FAILURES.inc()
            logger.warning(
                "pull of %s: no live source (causes: %s)",
                object_id.hex()[:12], causes,
            )
            return {"failed": True, "no_source": True, "causes": causes}
        size, digest = head["size"], head.get("digest")
        admitted = False
        allocated = False
        win = None
        try:
            admit_t0 = time.monotonic()
            await self._admit(size)
            _observe_pull_stage("admit", time.monotonic() - admit_t0)
            admitted = True
            # re-check after (possibly) queueing: a local put or adopt
            # may have landed while we were parked
            meta = self.store.ensure_local(object_id)
            if meta is not None:
                return {"segment": meta[0], "size": meta[1]}
            if not self.store.begin_receive(object_id):
                meta = self.store.ensure_local(object_id)
                if meta is not None:
                    return {"segment": meta[0], "size": meta[1]}
            self.store.allocate_receive(object_id, size)
            allocated = True
            # the writable window into the unsealed entry: RAW chunk
            # replies are received STRAIGHT into it (zero-copy receive)
            win = self.store.receive_window(object_id)
            buf = win.view
            offset, crc = 0, 0
            transfer_t0 = time.monotonic()
            while True:
                try:
                    offset, crc = await self._stream_from(
                        src, object_id, buf, size, offset, crc, plan
                    )
                except _SourceFailed as e:
                    causes[_addr(src)] = str(e)
                    # resume from the progress the failed source left
                    # behind — every chunk written to buf was verified
                    offset, crc = e.offset, e.crc
                    nxt = await self._next_source(
                        candidates, object_id, causes, size, digest
                    )
                    if nxt is None:
                        raise _PullAbort("every source failed")
                    if offset > 0:
                        PULL_RESUMES.inc()  # resumed, not restarted
                    src = nxt
                    continue
                # end-to-end gate before seal: the running crc over every
                # verified chunk must equal the source-advertised digest
                if digest is not None and crc != digest:
                    PULL_INTEGRITY_FAILURES.inc()
                    causes[_addr(src)] = "whole-object digest mismatch"
                    nxt = await self._next_source(
                        candidates, object_id, causes, size, digest
                    )
                    if nxt is None:
                        raise _PullAbort("every source failed")
                    src, offset, crc = nxt, 0, 0  # restart clean
                    continue
                break
            _observe_pull_stage("transfer", time.monotonic() - transfer_t0)
            self.store.seal_receive(object_id, digest=crc)
            meta = self.store.ensure_local(object_id)
            return {"segment": meta[0], "size": meta[1]}
        except _PullAbort as e:
            PULL_FAILURES.inc()
            # the abort reason must survive into the structured causes —
            # a deadline can expire with zero per-source entries yet
            causes.setdefault("deadline" if e.deadline else _addr(src), str(e))
            # ONE summary line for the whole pull, not a line per source
            logger.warning(
                "pull of %s failed: %s (causes: %s)",
                object_id.hex()[:12], e, causes,
            )
            return {
                "failed": True,
                "no_source": False,
                "deadline": e.deadline,
                "causes": causes,
            }
        finally:
            if win is not None:
                win.close()
            if allocated:
                self.store.abort_receive(object_id)  # no-op once sealed
            if admitted:
                self._release(size)

    async def _stream_from(
        self,
        src: _Source,
        object_id: ObjectID,
        buf,
        size: int,
        offset: int,
        crc: int,
        plan,
    ) -> Tuple[int, int]:
        """Stream chunks from one source into the destination segment
        starting at ``offset``. RAW replies land DIRECTLY in ``buf``'s
        chunk range (zero-copy receive); legacy pickled replies are
        copied in at commit. Returns the final (offset, crc) on
        completion; raises :class:`_SourceFailed` with progress already
        durable in ``buf`` (the caller resumes elsewhere).

        Visibility note: unverified bytes may transiently exist in the
        unsealed destination window (a RAW payload is written by the
        transport before its crc is checked), but a chunk only COMMITS —
        advancing offset and the running crc — after verification, and
        the entry stays invisible to every reader until seal. A failed
        check re-fetches into the same range."""
        from ray_tpu.observability.rpc_metrics import (
            PULL_CHUNK_RETRIES,
            PULL_CHUNKS,
            PULL_INTEGRITY_FAILURES,
            PULL_RAW_CHUNKS,
        )

        client = self._peer(src[0], src[1])
        chunk_bytes = GLOBAL_CONFIG.object_transfer_chunk_bytes
        depth = max(1, GLOBAL_CONFIG.pull_pipeline_depth)
        # pipelined prefetch (reference: pipelined 5 MiB chunks): up to
        # ``depth`` chunk requests ride the connection concurrently so
        # the wire stays busy while this side verifies + writes; the
        # commit order (and the running crc) stays strictly sequential.
        inflight: Dict[int, asyncio.Task] = {}
        next_sched = offset
        try:
            while offset < size:
                while next_sched < size and len(inflight) < depth:
                    ln = min(chunk_bytes, size - next_sched)
                    inflight[next_sched] = asyncio.ensure_future(
                        self._fetch_chunk_once(
                            client, object_id, next_sched, ln, plan,
                            into=buf[next_sched : next_sched + ln],
                        )
                    )
                    next_sched += ln
                length = min(chunk_bytes, size - offset)
                first_task = inflight.pop(offset, None)
                attempt = 0
                while True:
                    try:
                        if first_task is not None:
                            task, first_task = first_task, None
                            data = await task
                        else:
                            data = await self._fetch_chunk_once(
                                client, object_id, offset, length, plan,
                                into=buf[offset : offset + length],
                            )
                        break
                    except _ChunkIntegrityError:
                        PULL_INTEGRITY_FAILURES.inc()
                        reason = "integrity"
                    except (asyncio.TimeoutError, TimeoutError):
                        reason = "timeout"
                    except _ChaosChunkError:
                        reason = "chaos"
                    except _SourceFailed as e:
                        e.offset, e.crc = offset, crc  # stamp verified progress
                        raise
                    except KeyError as e:
                        # the source no longer holds the object (freed or
                        # evicted under it): not a retryable chunk fault
                        raise _SourceFailed(
                            f"source lost the object: {e}", offset=offset, crc=crc
                        )
                    except (ConnectionLost, OSError):
                        reason = "transport"
                    attempt += 1
                    if attempt > GLOBAL_CONFIG.pull_chunk_retries:
                        raise _SourceFailed(
                            f"chunk at offset {offset} exhausted "
                            f"{GLOBAL_CONFIG.pull_chunk_retries} retries ({reason})",
                            offset=offset,
                            crc=crc,
                        )
                    PULL_CHUNK_RETRIES.inc(labels={"reason": reason})
                    if not await backoff_sleep(attempt):
                        raise _PullAbort(
                            "deadline exhausted mid-transfer", deadline=True
                        )
                # chunk verified: commit it. Only now does the running crc
                # advance — a failover resumes exactly from here. The fold
                # uses crc32_combine over the already-VERIFIED chunk crc:
                # one matrix·vector multiply instead of a second full data
                # pass (util/crc.py) — the receiver touches each byte
                # exactly once.
                ln, chunk_crc, data = data
                if data is not None:
                    # legacy pickled reply: one copy into the window
                    buf[offset : offset + ln] = data
                else:
                    # counted at COMMIT, beside PULL_CHUNKS, so the
                    # raw==total tripwire holds even when a failover
                    # discards verified-but-uncommitted prefetches
                    PULL_RAW_CHUNKS.inc()
                crc = crc32_combine(crc, chunk_crc, ln)
                offset += ln
                PULL_CHUNKS.inc()
            return offset, crc
        finally:
            for t in inflight.values():
                t.cancel()
            if inflight:
                # retrieve cancellations/failures so abandoned prefetch
                # tasks never log "exception was never retrieved"
                await asyncio.gather(*inflight.values(), return_exceptions=True)

    async def _fetch_chunk_once(
        self, client, object_id: ObjectID, offset: int, length: int, plan,
        into=None,
    ):
        """One chunk attempt: chaos consult, bounded fetch, per-chunk
        integrity verification. RAW replies are received straight into
        ``into`` (a writable sub-view of the destination window) and
        verified THERE. Returns ``(length, verified_chunk_crc, data)``
        where ``data`` is None for RAW receives (payload already in the
        window) and the verified bytes for legacy pickled replies — the
        caller commits by folding the VERIFIED crc (no second data
        pass). Unverified bytes never COMMIT anywhere — a RAW payload
        transiently occupies its (unsealed, reader-invisible)
        destination range until its crc passes, and a failed check
        re-fetches into the same range."""
        from ray_tpu.core.rpc import RawReply

        mode = param = None
        if plan is not None:
            fault = plan.next_fault()
            if fault is not None:
                mode, param = fault
                _count_injection(mode)
                if mode == "chunk_drop":
                    raise _ChaosChunkError("chaos: chunk dropped")
                if mode == "source_die_mid_transfer":
                    raise _SourceFailed("chaos: source died mid-transfer")
                if mode == "chunk_stall":
                    # the fetch wedges past its timeout: the stall costs
                    # one chunk-timeout, then the retry machinery runs
                    await asyncio.sleep(param)
                    raise asyncio.TimeoutError("chaos: chunk stalled")
        timeout = effective_timeout(GLOBAL_CONFIG.pull_chunk_timeout_s)
        if timeout is not None and timeout <= 0:
            raise _PullAbort("deadline exhausted mid-transfer", deadline=True)
        reply = await client.call(
            "fetch_chunk",
            {
                "object_id": object_id.binary(),
                "offset": offset,
                "length": length,
                # announce zero-copy receive: a RAW-capable source answers
                # with an out-of-band payload framed for ``into``
                "raw": into is not None,
            },
            timeout=timeout,
            raw_into=into,
        )
        if isinstance(reply, RawReply):
            chunk_crc = reply.meta
            if reply.data is None and into is not None:
                # zero-copy receive: payload already sits in the
                # destination range — verify it in place (the receiver's
                # ONLY pass over the bytes)
                view = into[: reply.nbytes]
                if mode == "chunk_corrupt" and reply.nbytes:
                    # flip one byte AFTER the sender computed the crc: the
                    # verification below MUST catch it (that's the assertion)
                    view[reply.nbytes // 2] ^= 0xFF
                verified = zlib.crc32(view)
                if chunk_crc is not None and verified != chunk_crc:
                    raise _ChunkIntegrityError(
                        f"chunk crc mismatch at offset {offset}"
                    )
                if reply.nbytes != length:
                    raise _ChunkIntegrityError(
                        f"truncated chunk at offset {offset}: "
                        f"{reply.nbytes} != {length}"
                    )
                return reply.nbytes, verified, None
            # sink-less raw fallback (shouldn't happen on this path):
            # treat like a legacy reply
            data = bytes(reply.data or b"")
        elif isinstance(reply, (bytes, bytearray, memoryview)):
            data, chunk_crc = bytes(reply), None  # legacy sender (no crc)
        else:
            data, chunk_crc = reply
        if mode == "chunk_corrupt" and data:
            # flip one byte AFTER the sender computed the crc: the
            # verification below MUST catch it (that's the assertion)
            corrupted = bytearray(data)
            corrupted[len(corrupted) // 2] ^= 0xFF
            data = bytes(corrupted)
        verified = zlib.crc32(data)
        if chunk_crc is not None and verified != chunk_crc:
            raise _ChunkIntegrityError(f"chunk crc mismatch at offset {offset}")
        if len(data) != length:
            raise _ChunkIntegrityError(
                f"truncated chunk at offset {offset}: {len(data)} != {length}"
            )
        return len(data), verified, data
