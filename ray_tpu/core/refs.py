"""ObjectRef: a distributed future with an owner address.

Design (cf. reference ``ObjectRef`` in ``_raylet.pyx`` + ownership model in
``core_worker/reference_count.h:64``): a ref carries its ``ObjectID`` plus
the *owner* worker's address. The owner is the process that created the
object (by ``put`` or by submitting the producing task); it holds the
authoritative reference count, the value-or-location, and the lineage needed
for reconstruction. Any process holding a ref can resolve it by asking the
owner; deserializing a ref into a new process registers that process as a
*borrower* with the owner.

Refs deregister themselves on ``__del__`` through the ambient runtime (if
one is connected), driving distributed GC.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.core.ids import ObjectID, WorkerID


@dataclass(frozen=True)
class Address:
    """Location of a worker's RPC endpoint."""

    worker_id: bytes  # WorkerID binary
    node_id: bytes  # NodeID binary
    host: str
    port: int

    def key(self):
        return (self.worker_id, self.host, self.port)


class ObjectRef:
    __slots__ = ("_id", "_owner", "_skip_refcount", "__weakref__")

    def __init__(
        self,
        object_id: ObjectID,
        owner: Optional[Address] = None,
        *,
        _skip_refcount: bool = False,
        _borrowed: bool = False,
    ):
        self._id = object_id
        self._owner = owner
        # _borrowed refs register with the owner as borrowers on creation
        # and STILL deregister on __del__ (remove_local_ref routes to
        # remove_borrower for non-owned ids) — a deserialized ref must
        # participate in lifecycle or the owner pins the object forever.
        self._skip_refcount = _skip_refcount
        if _borrowed:
            _runtime_register_borrow(self)
        elif not _skip_refcount:
            _runtime_add_local_ref(self)

    # -- identity --------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    @property
    def owner_address(self) -> Optional[Address]:
        return self._owner

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and self._id == other._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    # -- pickling: travels with owner address; registers borrower --------
    def __reduce__(self):
        return (_deserialize_ref, (self._id.binary(), self._owner))

    # -- lifecycle -------------------------------------------------------
    def __del__(self):
        if not self._skip_refcount:
            _runtime_remove_local_ref(self)

    # -- ergonomics ------------------------------------------------------
    def future(self):
        """A concurrent.futures.Future resolving to the value."""
        from ray_tpu.core.api import _global_worker

        return _global_worker().to_future(self)

    def __await__(self):
        from ray_tpu.core.api import _global_worker

        return _global_worker().await_ref(self).__await__()


def _deserialize_ref(binary: bytes, owner: Optional[Address]) -> ObjectRef:
    return ObjectRef(ObjectID(binary), owner, _borrowed=True)


# --- hooks into the ambient runtime (set by api.init) -------------------

_hooks = {"add": None, "remove": None, "borrow": None}


def set_refcount_hooks(add, remove, borrow) -> None:
    _hooks["add"], _hooks["remove"], _hooks["borrow"] = add, remove, borrow


def _runtime_add_local_ref(ref: ObjectRef) -> None:
    if _hooks["add"] is not None:
        _hooks["add"](ref)


def _runtime_remove_local_ref(ref: ObjectRef) -> None:
    if _hooks["remove"] is not None:
        try:
            _hooks["remove"](ref)
        except Exception:
            pass  # interpreter shutdown


def _runtime_register_borrow(ref: ObjectRef) -> None:
    if _hooks["borrow"] is not None:
        _hooks["borrow"](ref)
