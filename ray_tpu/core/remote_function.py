"""``@remote`` functions (cf. reference ``python/ray/remote_function.py``)."""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

from ray_tpu.core.api import _global_worker
from ray_tpu.core.task_spec import TaskKind, TaskOptions


class RemoteFunction:
    def __init__(self, function: Callable, opts: Optional[TaskOptions] = None):
        if not callable(function):
            raise TypeError("@remote requires a callable")
        self._function = function
        self._opts = opts or TaskOptions()
        self._name = getattr(function, "__qualname__", getattr(function, "__name__", "fn"))
        # cached task-spec template (invariant spec fields serialized
        # once; per-call fields spliced at submit). False = shape not
        # templatable (streaming / runtime_env) — don't retry per call.
        self._template = None
        functools.update_wrapper(self, function, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._name}() cannot be called directly; "
            f"use {self._name}.remote()"
        )

    def remote(self, *args, **kwargs):
        worker = _global_worker()
        tmpl = self._template
        if tmpl is False:
            return worker.submit_task(self._function, self._name, args, kwargs, self._opts)
        if not worker.template_current(tmpl):
            tmpl = worker.make_spec_template(
                TaskKind.NORMAL, self._function, self._name, self._opts
            )
            self._template = tmpl if tmpl is not None else False
            if tmpl is None:
                return worker.submit_task(
                    self._function, self._name, args, kwargs, self._opts
                )
        return worker.submit_from_template(tmpl, args, kwargs)

    def options(self, **updates) -> "RemoteFunction":
        return RemoteFunction(self._function, self._opts.merged_with(**updates))

    def bind(self, *args, **kwargs):
        """DAG-node construction (compiled graphs)."""
        try:
            from ray_tpu.dag.node import FunctionNode
        except ImportError as e:
            raise NotImplementedError(
                "ray_tpu.dag (compiled graphs) is not available in this build"
            ) from e

        return FunctionNode(self, args, kwargs)

    @property
    def func(self) -> Callable:
        """The underlying (undecorated) function."""
        return self._function
