"""Cluster resource model.

Equivalent of the reference's scheduling resource types
(``src/ray/common/scheduling/``): named resources held as fixed-point
integers (1 unit = 1/10000) so fractional requests compose without float
drift; ``NodeResources`` tracks total vs. available; ``ResourceRequest`` is
what a task/actor/bundle demands.

TPU-first addition: the well-known resource names include ``TPU`` (chips on
a host) and per-topology slice head resources like ``TPU-v5e-8-head`` which
gang-scheduling uses to place exactly one coordinator per pod slice
(cf. reference ``python/ray/_private/accelerators/tpu.py``).
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional

PRECISION = 10_000

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

# Shadow-resource naming for placement group bundles (reference:
# ``CPU_group_<pgid>`` in ``raylet/placement_group_resource_manager.cc``).
def pg_resource_name(resource: str, pg_id_hex: str, bundle_index: Optional[int] = None) -> str:
    if bundle_index is None:
        return f"{resource}_group_{pg_id_hex}"
    return f"{resource}_group_{bundle_index}_{pg_id_hex}"


def is_pg_resource(name: str) -> bool:
    return "_group_" in name


def tpu_slice_head_resource(topology: str) -> str:
    """e.g. ``TPU-v5e-8-head``: one per slice, claimed by the gang leader."""
    return f"TPU-{topology}-head"


def to_fixed(value: float) -> int:
    return round(value * PRECISION)


def from_fixed(value: int) -> float:
    return value / PRECISION


class ResourceSet:
    """Immutable-ish map of resource name -> fixed-point amount (> 0)."""

    __slots__ = ("_map",)

    def __init__(self, amounts: Optional[Mapping[str, float]] = None, _fixed: Optional[Dict[str, int]] = None):
        if _fixed is not None:
            self._map = {k: v for k, v in _fixed.items() if v > 0}
        else:
            self._map = {}
            for name, value in (amounts or {}).items():
                if value < 0:
                    raise ValueError(f"negative resource {name}: {value}")
                fixed = to_fixed(value)
                if fixed > 0:
                    self._map[name] = fixed

    def get(self, name: str) -> float:
        return from_fixed(self._map.get(name, 0))

    def get_fixed(self, name: str) -> int:
        return self._map.get(name, 0)

    def names(self) -> Iterable[str]:
        return self._map.keys()

    def is_empty(self) -> bool:
        return not self._map

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._map.items()}

    def fixed_items(self):
        return self._map.items()

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceSet) and self._map == other._map

    def __repr__(self) -> str:
        return f"ResourceSet({self.to_dict()})"

    def covers(self, request: "ResourceSet") -> bool:
        return all(self._map.get(k, 0) >= v for k, v in request._map.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._map)
        for k, v in other._map.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet(_fixed=out)

    def subtract(self, other: "ResourceSet", allow_negative: bool = False) -> "ResourceSet":
        out = dict(self._map)
        for k, v in other._map.items():
            nv = out.get(k, 0) - v
            if nv < 0 and not allow_negative:
                raise ValueError(f"resource {k} would go negative")
            if nv <= 0:
                out.pop(k, None)
            else:
                out[k] = nv
        return ResourceSet(_fixed=out)


class NodeResources:
    """Total and available resources of one node, plus node labels."""

    __slots__ = ("total", "available", "labels")

    def __init__(self, total: ResourceSet, labels: Optional[Dict[str, str]] = None):
        self.total = total
        self.available = ResourceSet(_fixed=dict(total.fixed_items()))
        self.labels = labels or {}

    def can_fit(self, request: ResourceSet) -> bool:
        return self.available.covers(request)

    def could_ever_fit(self, request: ResourceSet) -> bool:
        return self.total.covers(request)

    def allocate(self, request: ResourceSet) -> None:
        self.available = self.available.subtract(request)

    def release(self, request: ResourceSet) -> None:
        self.available = self.available.add(request)
        # Clamp to total (release after total shrank, e.g. PG removal).
        clamped = {}
        for k, v in self.available.fixed_items():
            clamped[k] = min(v, self.total.get_fixed(k)) if self.total.get_fixed(k) else v
        self.available = ResourceSet(_fixed=clamped)

    def add_total(self, extra: ResourceSet) -> None:
        self.total = self.total.add(extra)
        self.available = self.available.add(extra)

    def remove_total(self, extra: ResourceSet) -> None:
        self.total = self.total.subtract(extra, allow_negative=True)
        self.available = self.available.subtract(extra, allow_negative=True)

    def utilization(self) -> float:
        """Max over resources of used/total — the hybrid policy's node score
        (reference ``scorer.h:41`` LeastResourceScorer)."""
        worst = 0.0
        for name, total in self.total.fixed_items():
            if is_pg_resource(name) or total <= 0:
                continue
            used = total - self.available.get_fixed(name)
            worst = max(worst, used / total)
        return worst

    def to_dict(self) -> Dict[str, Dict[str, float]]:
        return {"total": self.total.to_dict(), "available": self.available.to_dict()}
