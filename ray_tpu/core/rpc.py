"""Asyncio RPC: the control- and data-plane transport.

Equivalent of the reference's gRPC layer (``src/ray/rpc/grpc_server.h``,
``rpc/client_call.h``, retrying client, fault injection
``rpc/rpc_chaos.h:23``) redesigned for this runtime: length-prefixed
msgpack frames over TCP, one asyncio server per process, typed async
handlers, a retrying client with exponential backoff, server-push
subscription streams (the pubsub substrate), and env-configurable chaos
injection for tests.

Frame format (all little-endian):
    [u32 length] [msgpack: [kind, seq, method, payload_bytes]]

kinds: 0=request, 1=reply-ok, 2=reply-err, 3=push (server-initiated,
seq identifies the subscription), 4=batch (micro-batching: the payload
slot carries a FIFO list of packed sub-frame bodies — a flush coalesces
every frame queued on a connection into batch frames, and the receiver
dispatches all of them from ONE read wakeup instead of a wakeup per
frame; per-connection FIFO order is preserved).
Payloads are pickled (cloudpickle-compatible dataclasses travel as-is);
the store's bulk data paths use raw bytes to avoid copies.
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import random
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu.core.config import GLOBAL_CONFIG

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
REQUEST, REPLY_OK, REPLY_ERR, PUSH, BATCH = 0, 1, 2, 3, 4

MAX_FRAME = 1 << 31


#: corked writes flush early past this many buffered bytes (keeps
#: drain()'s flow-control view at most one small flush stale)
_FLUSH_BYTES = 1 << 20


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteError(RpcError):
    """Handler raised on the server; message carries the repr."""


class ChaosInjectedError(ConnectionLost):
    """Injected fault (``testing_rpc_failure``). A ConnectionLost
    subclass so every retry path treats it as a transient transport
    failure — the reference rpc_chaos contract: injected failures are
    RETRIED by the retrying client (they fire BEFORE the handler runs,
    so a retry never double-executes), exercising retry handling rather
    than fabricating app-level errors."""


def _chaos_should_fail(method: str) -> bool:
    """Fault injection (reference ``RAY_testing_rpc_failure``)."""
    spec = GLOBAL_CONFIG.testing_rpc_failure
    if not spec:
        return False
    try:
        name, prob = spec.split(":")
    except ValueError:
        return False
    return (name == "*" or name == method) and random.random() < float(prob)


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    data = await reader.readexactly(length)
    return msgpack.unpackb(data, raw=True, use_list=True)


def _iter_messages(msg):
    """Expand one wire frame into its logical messages: a BATCH frame's
    payload slot is the FIFO list of packed sub-frame bodies; anything
    else is itself. Batches never nest."""
    if msg[0] != BATCH:
        yield msg
        return
    for body in msg[3]:
        yield msgpack.unpackb(body, raw=True, use_list=True)


def _encode_body(kind: int, seq: int, method: bytes, payload: bytes) -> bytes:
    """A frame body WITHOUT the length prefix (the unit of batching)."""
    return msgpack.packb([kind, seq, method, payload], use_bin_type=True)


def _encode_frame(kind: int, seq: int, method: bytes, payload: bytes) -> bytes:
    body = _encode_body(kind, seq, method, payload)
    return _LEN.pack(len(body)) + body


def _wire_from_bodies(bodies: list) -> bytes:
    """Serialize a FIFO list of frame bodies for one send: consecutive
    bodies coalesce into BATCH frames up to ``rpc_batch_max_frames`` /
    ``rpc_batch_max_bytes``; singletons travel as plain frames. Order on
    the wire is exactly the queue order, so per-connection FIFO holds."""
    max_frames = GLOBAL_CONFIG.rpc_batch_max_frames
    max_bytes = GLOBAL_CONFIG.rpc_batch_max_bytes
    if len(bodies) == 1 or max_frames <= 1:
        return b"".join(_LEN.pack(len(b)) + b for b in bodies)
    out: list = []
    group: list = []
    group_bytes = 0

    def close():
        nonlocal group, group_bytes
        if not group:
            return
        if len(group) == 1:
            body = group[0]
        else:
            body = msgpack.packb([BATCH, 0, b"", group], use_bin_type=True)
        out.append(_LEN.pack(len(body)))
        out.append(body)
        group = []
        group_bytes = 0

    for body in bodies:
        if group and (
            len(group) >= max_frames or group_bytes + len(body) > max_bytes
        ):
            close()
        group.append(body)
        group_bytes += len(body)
    close()
    return b"".join(out)


class RpcServer:
    """Async RPC server. Handlers: ``async def h(payload, ctx) -> result``.

    ``ctx`` is the per-connection ``ServerConnection`` — handlers use it to
    register push subscriptions or learn the peer.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[bytes, Callable[[Any, "ServerConnection"], Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self.on_disconnect: Optional[Callable[["ServerConnection"], None]] = None

    def register(self, method: str, handler) -> None:
        self._handlers[method.encode()] = handler

    async def start(self) -> int:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        # handler timing registry (reference event_stats.h): every dispatch
        # below records queueing + run latency under the method name
        from ray_tpu.observability.event_stats import GLOBAL_EVENT_STATS

        GLOBAL_EVENT_STATS.ensure_metrics()
        return self.port

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn = ServerConnection(reader, writer)
        self._conns.add(conn)
        try:
            while True:
                try:
                    msg = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
                    break
                # a BATCH frame dispatches all its requests from this ONE
                # read wakeup, in queue order (micro-batching)
                enqueued_at = time.monotonic()
                for kind, seq, method, payload in _iter_messages(msg):
                    if kind != REQUEST:
                        continue
                    asyncio.ensure_future(
                        self._dispatch(conn, seq, method, payload, enqueued_at)
                    )
        finally:
            self._conns.discard(conn)
            conn._closed = True
            if self.on_disconnect:
                try:
                    self.on_disconnect(conn)
                except Exception:
                    logger.exception("on_disconnect callback failed")
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn: "ServerConnection", seq: int, method: bytes, payload: bytes, enqueued_at: float = 0.0):
        from ray_tpu.observability.event_stats import GLOBAL_EVENT_STATS

        handler = self._handlers.get(method)
        started_at = time.monotonic()
        try:
            if handler is None:
                raise RpcError(f"no handler for {method.decode()!r}")
            if _chaos_should_fail(method.decode()):
                raise ChaosInjectedError(
                    f"chaos: injected failure for {method.decode()}"
                )
            arg = pickle.loads(payload) if payload else None
            result = await handler(arg, conn)
            await conn.send(REPLY_OK, seq, method, pickle.dumps(result, protocol=5))
        except Exception as e:  # noqa: BLE001 — reply with the error
            try:
                await conn.send(REPLY_ERR, seq, method, pickle.dumps(e))
            except Exception:
                logger.debug("failed to send error reply", exc_info=True)
        finally:
            GLOBAL_EVENT_STATS.record(
                method.decode(errors="replace"),
                started_at - enqueued_at if enqueued_at else 0.0,
                time.monotonic() - started_at,
            )

    async def stop(self) -> None:
        # Close live connections first: in py3.12 ``wait_closed`` waits for
        # all of them, so the order matters.
        for conn in list(self._conns):
            conn._closed = True
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass


class ServerConnection:
    """Server side of one client connection; supports push messages.

    Writes are CORKED: frames buffer per connection and flush once per
    loop tick, coalescing replies into one send syscall (syscalls cost
    ~100µs on virtualized hosts — per-reply writes dominated the task
    round-trip before batching)."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self._closed = False
        self._out: list = []
        self._flush_scheduled = False
        self.peer_tags: Dict[str, Any] = {}  # handlers stash identity here

    async def send(self, kind: int, seq: int, method: bytes, payload: bytes) -> None:
        if self._closed:
            raise ConnectionLost("connection closed")
        body = _encode_body(kind, seq, method, payload)
        self._out.append(body)
        self._out_bytes = getattr(self, "_out_bytes", 0) + len(body)
        if self._out_bytes >= _FLUSH_BYTES:
            # large buffers flush NOW: the cork trades one loop tick of
            # latency for syscall coalescing, but drain()'s flow control
            # only sees written bytes — an unbounded cork defeats it
            self._flush()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)
        await self.writer.drain()

    def _flush(self) -> None:
        self._flush_scheduled = False
        if not self._out or self._closed:
            self._out.clear()
            return
        bodies, self._out = self._out, []
        self._out_bytes = 0
        try:
            # queued frames coalesce into batch frames: the peer gets one
            # read wakeup for the whole flush (micro-batching)
            self.writer.write(_wire_from_bodies(bodies))
        except Exception:
            # mark closed so subsequent sends fail fast instead of
            # buffering into a dead socket until the reader notices
            self._closed = True

    async def push(self, channel: int, payload: Any) -> None:
        """Server-initiated message on a subscription channel."""
        await self.send(PUSH, channel, b"", pickle.dumps(payload, protocol=5))

    @property
    def closed(self) -> bool:
        return self._closed


class RpcClient:
    """Retrying client (reference retryable gRPC client): reconnects with
    exponential backoff; in-flight calls fail with ConnectionLost unless
    the method is marked retryable."""

    def __init__(self, host: str, port: int, *, name: str = ""):
        self.host = host
        self.port = port
        self.name = name or f"{host}:{port}"
        self._reader = None
        self._writer = None
        self._seq = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._push_handlers: Dict[int, Callable[[Any], None]] = {}
        self._conn_lock: Optional[asyncio.Lock] = None
        self._read_task: Optional[asyncio.Task] = None
        self._closed = False
        # write cork (see ServerConnection): frames issued in one loop
        # tick coalesce into a single send syscall
        self._out: list = []
        self._flush_scheduled = False

    async def _ensure_connected(self, connect_timeout: Optional[float] = None):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            deadline = time.monotonic() + (
                connect_timeout if connect_timeout is not None else GLOBAL_CONFIG.rpc_connect_timeout_s
            )
            delay = GLOBAL_CONFIG.rpc_retry_base_delay_s
            while True:
                try:
                    self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
                    break
                except OSError:
                    if time.monotonic() > deadline or self._closed:
                        raise ConnectionLost(f"cannot connect to {self.name}")
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, GLOBAL_CONFIG.rpc_retry_max_delay_s)
            if self._read_task is not None:
                self._read_task.cancel()
            # Fresh pending map per connection: a stale read loop's cleanup
            # must never fail calls issued on a newer connection.
            self._pending = {}
            self._read_task = asyncio.ensure_future(
                self._read_loop(self._reader, self._writer, self._pending)
            )

    async def _read_loop(self, reader, writer, pending):
        try:
            while True:
                msg = await _read_frame(reader)
                for kind, seq, method, payload in _iter_messages(msg):
                    if kind == PUSH:
                        handler = self._push_handlers.get(seq)
                        if handler is not None:
                            try:
                                handler(pickle.loads(payload))
                            except Exception:
                                logger.exception("push handler failed")
                        continue
                    fut = pending.pop(seq, None)
                    if fut is None or fut.done():
                        continue
                    if kind == REPLY_OK:
                        fut.set_result(pickle.loads(payload))
                    else:
                        fut.set_exception(pickle.loads(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"connection to {self.name} lost"))
            pending.clear()
            try:
                writer.close()
            except Exception:
                pass
            if self._writer is writer:
                self._writer = None

    def subscribe_push(self, channel: int, handler: Callable[[Any], None]) -> None:
        self._push_handlers[channel] = handler

    async def call(
        self,
        method: str,
        payload: Any = None,
        *,
        timeout: Optional[float] = None,
        retries: int = 0,
        connect_timeout: Optional[float] = None,
    ):
        attempt = 0
        delay = GLOBAL_CONFIG.rpc_retry_base_delay_s
        while True:
            try:
                return await self._call_once(method, payload, timeout, connect_timeout)
            except (ConnectionLost, asyncio.TimeoutError):
                attempt += 1
                if attempt > retries or self._closed:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, GLOBAL_CONFIG.rpc_retry_max_delay_s)

    async def _call_once(self, method: str, payload: Any, timeout: Optional[float], connect_timeout: Optional[float] = None):
        await self._ensure_connected(connect_timeout)
        self._seq += 1
        seq = self._seq
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[seq] = fut
        try:
            body = _encode_body(
                REQUEST, seq, method.encode(), pickle.dumps(payload, protocol=5)
            )
            self._out.append(body)
            self._out_bytes = getattr(self, "_out_bytes", 0) + len(body)
            if self._out_bytes >= _FLUSH_BYTES:
                self._flush()  # see ServerConnection.send: bound the cork
            elif not self._flush_scheduled:
                self._flush_scheduled = True
                asyncio.get_event_loop().call_soon(self._flush)
            await self._writer.drain()
        except (ConnectionResetError, BrokenPipeError, AttributeError) as e:
            self._pending.pop(seq, None)
            raise ConnectionLost(str(e))
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def _flush(self) -> None:
        self._flush_scheduled = False
        writer = self._writer
        if not self._out or writer is None:
            self._out.clear()
            self._out_bytes = 0
            return
        bodies, self._out = self._out, []
        self._out_bytes = 0
        try:
            # one write, frames coalesced into batch frames (micro-batching)
            writer.write(_wire_from_bodies(bodies))
        except Exception:
            # fail in-flight calls NOW — waiting for the read loop to
            # notice the dead socket can add a full timeout of latency
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"write to {self.name} failed"))
            self._pending.clear()
            try:
                writer.close()
            except Exception:
                pass
            if self._writer is writer:
                self._writer = None

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class IoThread:
    """A dedicated thread running an asyncio loop; the per-process event
    loop that all RPC clients/servers of a (sync) process live on.

    Reference analogue: the per-process asio io_context with instrumented
    handlers (``common/event_stats.h``)."""

    def __init__(self, name: str = "ray-tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.monitor = None  # LoopMonitor (stall watchdog), set in _run
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        from concurrent.futures import ThreadPoolExecutor

        asyncio.set_event_loop(self.loop)
        # Long-poll handlers park in the default executor; the stock pool
        # (cpu+4 threads) is far too small under many concurrent waiters.
        self.loop.set_default_executor(ThreadPoolExecutor(max_workers=64, thread_name_prefix="io-exec"))
        # Stall watchdog (hang defense): a handler blocking THIS loop is
        # invisible from outside — the monitor's heartbeat + off-loop
        # watchdog turns "process frozen" into a named stack dump.
        from ray_tpu.observability.event_stats import install_loop_monitor

        self.monitor = install_loop_monitor(self.loop, self._thread.name)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the io loop from a sync context."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def post(self, coro) -> None:
        asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        # detach the watchdog FIRST: a stopping loop's silent heartbeat
        # must not be reported (or worse, aborted) as a stall
        from ray_tpu.observability.event_stats import remove_loop_monitor

        remove_loop_monitor(self.loop)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)
