"""Asyncio RPC: the control- and data-plane transport.

Equivalent of the reference's gRPC layer (``src/ray/rpc/grpc_server.h``,
``rpc/client_call.h``, retrying client, fault injection
``rpc/rpc_chaos.h:23``) redesigned for this runtime: length-prefixed
msgpack frames over TCP, one asyncio server per process, typed async
handlers, a retrying client with jittered exponential backoff capped by
the ambient ``core/deadline`` budget, server-push subscription streams
(the pubsub substrate), request-id dedup for exactly-once-effective
mutating RPCs, and seeded, config-driven chaos injection for tests.

Frame format (all little-endian):
    [u32 length] [msgpack: [kind, seq, method, payload_bytes, meta?]]

kinds: 0=request, 1=reply-ok, 2=reply-err, 3=push (server-initiated,
seq identifies the subscription), 4=batch (micro-batching: the payload
slot carries a FIFO list of packed sub-frame bodies — a flush coalesces
every frame queued on a connection into batch frames, and the receiver
dispatches all of them from ONE read wakeup instead of a wakeup per
frame; per-connection FIFO order is preserved), 5=raw (zero-copy bulk
payload framing, below).
Payloads are pickled (cloudpickle-compatible dataclasses travel as-is);
the store's bulk data paths use RAW frames to avoid copies.

RAW frames (kind 5) — the zero-copy data plane. The header stays a
length-prefixed msgpack body, but the bulk payload travels OUT OF BAND
as raw bytes immediately after it:

    [u32 header_len] [msgpack: [5, seq, method, payload_len, meta]]
    [payload_len raw bytes]

The sender never concatenates header and payload: ``send_raw`` queues
the header plus the payload ``memoryview`` and the flush writes them
back to back (writev-style scatter-gather — the payload goes to the
socket straight from its source buffer, e.g. a shm segment). The
receiver reads ``payload_len`` bytes off the stream DIRECTLY into a
caller-provided buffer (``call(..., raw_into=view)``), so a chunk reply
lands in the destination shm segment with zero intermediate full-size
``bytes``. A non-empty ``method`` marks a RAW *reply* (seq matches a
pending call); an empty method marks a RAW *push* (seq is the
subscription channel, meta is the pickled envelope dict — the payload
is delivered as ``envelope["data"]``). RAW frames never batch, and RAW
replies never enter the request-dedup reply cache (one multi-MiB bulk
reply would evict the entire 32 MiB control-plane window) — the bulk
methods are idempotent reads, so a retried RAW call simply re-executes.

Exactly-once-effective mutating RPCs: a lost *reply* is
indistinguishable from a lost *request*, so a blind retry of a mutating
method duplicates its side effect. Requests for methods not classified
in :data:`IDEMPOTENT_METHODS` (per SERVER ROLE — the client is tagged
with the role it talks to) therefore carry a 5th frame slot
``meta = [client_id, request_id]`` (stable across every retry of one
logical call); the server keeps a bounded reply cache keyed on that
pair and answers duplicates from it instead of re-executing the
handler. Duplicates racing the ORIGINAL execution await its in-flight
future. The cache is bounded (``rpc_dedup_cache_entries`` /
``rpc_dedup_cache_max_bytes``, oldest-first eviction) — a retry
arriving after eviction re-executes, the same window the reference
accepts for its GCS-side dedup tables.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import os
import pickle
import random
import struct
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import msgpack

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.observability import tracing as _tracing

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
REQUEST, REPLY_OK, REPLY_ERR, PUSH, BATCH, RAW = 0, 1, 2, 3, 4, 5

MAX_FRAME = 1 << 31

#: receive-loop copy granularity for out-of-band RAW payloads: each
#: ``reader.read`` returns at most one buffer of roughly this size which
#: is immediately copied into the destination view — bounded transient
#: allocations, never a full-payload bytes object
_RAW_READ_CHUNK = 1 << 20


#: corked writes flush early past this many buffered bytes (keeps
#: drain()'s flow-control view at most one small flush stale)
_FLUSH_BYTES = 1 << 20

#: Methods safe to blind-retry because re-execution is a no-op (pure
#: reads, monotonic position reports, pop-style releases), NAMESPACED BY
#: SERVER ROLE: idempotency is a property of one service's handler, not
#: of a method NAME — "stats" being a pure read on the node daemon says
#: nothing about a future mutating "stats" on some other server, and a
#: process-global set would silently skip dedup for it (the PR 5 review
#: finding this fixes). Clients are tagged with the role of the server
#: they talk to (``RpcClient(role=...)``); everything not listed for
#: that role gets request-id stamping — the safe default for
#: unknown/mutating methods. Idempotent methods retry without cache
#: churn, mutating methods retry through the reply cache.
IDEMPOTENT_METHODS: Dict[str, frozenset] = {
    # the cluster controller (core/controller.py, c_* handlers)
    "controller": frozenset(
        {
            # liveness / subscriptions (re-subscribe is safe)
            "ping", "subscribe", "event_stats",
            # periodic state sync (latest-wins by construction)
            "sync_resources",
            # pure reads
            "nodes", "cluster_resources", "available_resources",
            "autoscaler_demand", "kv_get", "kv_keys", "get_actor_info",
            "get_named_actor", "list_named_actors", "get_pg",
            "get_named_pg", "pg_table", "list_tasks", "list_actors",
            "list_objects", "get_relocated", "cluster_status",
            "cluster_telemetry", "collect_events",
            # idempotent-by-construction: timeline export chunks are
            # keyed by (exporter, pid, chunk) — a retried export
            # overwrites its own entry
            "export_events",
            # idempotently guarded (DRAINING is a terminal latch)
            "drain_node",
        }
    ),
    # node daemons (core/node_daemon.py, d_* handlers)
    "noded": frozenset(
        {
            "ping", "hello", "event_stats", "stats", "metrics_text",
            # pure reads over the object directory/store. fetch_chunk /
            # object_info / get_object_meta MUST stay here: dedup-stamped
            # replies enter the bounded reply cache, and one multi-MiB
            # chunk reply per request would evict every cached
            # control-plane reply from the 32 MiB window (data-plane
            # bulk replies never belong in the dedup cache)
            "list_objects", "get_object_meta", "object_info",
            "fetch_chunk",
            # idempotent-by-construction object/worker ops
            "pull_object", "adopt_object", "delete_object",
            "kill_worker", "return_lease",
            # KV-tier registry: get/list are pure reads; put/del are
            # last-write-wins upserts/deletes keyed by content digest,
            # so a blind retry converges to the same registry state
            "kv_tier_get", "kv_tier_list", "kv_tier_put", "kv_tier_del",
            # idempotently guarded (per-worker released-state latch):
            # blind retries re-observe, never double-release
            "worker_blocked", "worker_unblocked",
            # drain entry point is idempotently guarded
            "drain",
        }
    ),
    # core workers (core/core_worker.py, w_* handlers)
    "worker": frozenset(
        {
            "ping",
            # pure reads / monotonic position reports
            "get_object_status", "stream_consumed",
            # idempotent-by-construction ops
            "cancel_task", "cancel_owned_task", "recover_object",
            "delete_object", "exit", "set_accelerator_env",
        }
    ),
}

#: legacy union view for UNTAGGED clients (ad-hoc tools, tests driving a
#: bare RpcServer): preserves the pre-namespacing classification rather
#: than changing their wire behavior under them. Runtime clients are all
#: role-tagged and get the per-role set.
_IDEMPOTENT_ANY = frozenset().union(*IDEMPOTENT_METHODS.values())


def idempotent_methods(role: Optional[str] = None) -> frozenset:
    """The idempotent-method classification for one server role; the
    legacy union for ``None``/unknown roles (see above)."""
    if role is None:
        return _IDEMPOTENT_ANY
    return IDEMPOTENT_METHODS.get(role, _IDEMPOTENT_ANY)


#: chaos retries use a short flat sleep (the server is demonstrably
#: alive — injected faults are not congestion) and a generous attempt
#: cap so sub-1.0 probabilities converge with overwhelming probability
_CHAOS_RETRY_CAP = 25
_CHAOS_RETRY_SLEEP_S = 0.02


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteError(RpcError):
    """Handler raised on the server; message carries the repr."""


class ChaosInjectedError(ConnectionLost):
    """Injected fault (``testing_rpc_failure`` / ``testing_rpc_chaos``).
    A ConnectionLost subclass so every retry path treats it as a
    transient transport failure. ``request_drop`` faults fire BEFORE the
    handler runs (a retry never double-executes); ``reply_drop`` faults
    fire AFTER — the handler ran, and only the request-id dedup cache
    makes the retry safe for mutating methods."""


class StaleControllerError(ConnectionLost):
    """A write stamped with a controller incarnation epoch LOWER than
    the highest the receiver has seen (``stale_controller``): the sender
    is a deposed controller and must exit instead of double-writing.
    Also raised by a controller that lost its OWN lease (self-fencing —
    it stops acking mutations before a standby can assume the lease
    expired). A ConnectionLost subclass so ordinary clients caught in a
    failover window simply retry and land on the new incumbent; a
    deposed controller's daemon clients run with zero retries, so the
    fence surfaces to it directly."""

    def __init__(self, msg: str, *, seen_epoch: int = 0):
        super().__init__(msg)
        #: highest epoch the rejecting side had seen (0 = unknown)
        self.seen_epoch = seen_epoch


#: the (client_id, request_id) dedup key of the RPC currently being
#: executed by this task's handler, or None. The controller's WAL reads
#: it to journal the acked reply alongside the mutation, so replay
#: re-seeds the exactly-once cache (see core/wal.py).
_CURRENT_DEDUP_KEY: contextvars.ContextVar = contextvars.ContextVar(
    "rpc_dedup_key", default=None
)


def current_dedup_key() -> Optional[Tuple[bytes, int]]:
    """Dedup key of the in-flight RPC on this task, if any (handlers
    only — None outside a deduped dispatch)."""
    return _CURRENT_DEDUP_KEY.get()


#: Linux-only privileged setsockopt variants that bypass wmem_max/rmem_max
_SO_SNDBUFFORCE = 32
_SO_RCVBUFFORCE = 33


def _tune_transport(writer: asyncio.StreamWriter) -> None:
    """Best-effort per-connection throughput tuning: big kernel socket
    buffers (so a multi-MiB RAW payload goes to the kernel in one send
    instead of being memcpy'd into the asyncio write buffer) and a
    matching transport write high-water mark (fewer drain round-trips).
    Failures are ignored — the connection works either way, just slower."""
    import socket as _socket

    buf = GLOBAL_CONFIG.rpc_socket_buffer_bytes
    if buf <= 0:
        return
    sock = writer.get_extra_info("socket")
    if sock is not None:
        # the FORCE variants are Linux-only option NUMBERS — on other
        # platforms 32/33 name unrelated options (e.g. SO_BROADCAST on
        # BSD/macOS), so never issue them there
        is_linux = sys.platform.startswith("linux")
        for force_opt, opt in (
            (_SO_SNDBUFFORCE if is_linux else None, _socket.SO_SNDBUF),
            (_SO_RCVBUFFORCE if is_linux else None, _socket.SO_RCVBUF),
        ):
            try:
                if force_opt is None:
                    raise OSError
                sock.setsockopt(_socket.SOL_SOCKET, force_opt, buf)
            except OSError:
                try:
                    sock.setsockopt(_socket.SOL_SOCKET, opt, buf)
                except OSError:
                    pass
    try:
        writer.transport.set_write_buffer_limits(high=buf)
    except Exception:
        pass


def _chaos_should_fail(method: str) -> bool:
    """Legacy pre-handler fault injection (reference
    ``RAY_testing_rpc_failure``)."""
    spec = GLOBAL_CONFIG.testing_rpc_failure
    if not spec:
        return False
    try:
        name, prob = spec.split(":")
    except ValueError:
        return False
    return (name == "*" or name == method) and random.random() < float(prob)


_PLAN_CACHE = None
_PLAN_CACHE_LOCK = threading.Lock()


def active_fault_plan():
    """The process-wide seeded fault plan for ``testing_rpc_chaos`` (or
    None). Built lazily and rebuilt when the spec/seed config changes;
    the seed is logged at activation so a failure reproduces from the
    log alone (set ``RAY_TPU_testing_rpc_chaos_seed`` to replay) —
    util/chaos.py::SeededPlanCache."""
    global _PLAN_CACHE
    if _PLAN_CACHE is None:
        from ray_tpu.util.chaos import RpcFaultPlan, SeededPlanCache

        with _PLAN_CACHE_LOCK:
            if _PLAN_CACHE is None:
                _PLAN_CACHE = SeededPlanCache(
                    RpcFaultPlan, "rpc",
                    "testing_rpc_chaos", "testing_rpc_chaos_seed", logger,
                )
    return _PLAN_CACHE.active()


def _next_fault(method: str) -> Optional[Tuple[str, float]]:
    """Consult both chaos knobs for this dispatch: the legacy
    ``testing_rpc_failure`` (a request_drop) and the seeded fault plan."""
    if _chaos_should_fail(method):
        return ("request_drop", 0.0)
    plan = active_fault_plan()
    if plan is None:
        return None
    return plan.next_fault(method)


def _count_injection(mode: str) -> None:
    from ray_tpu.observability.rpc_metrics import RPC_CHAOS_INJECTIONS

    RPC_CHAOS_INJECTIONS.inc(labels={"mode": mode})


class RawPayload:
    """A handler's (or push sender's) zero-copy bulk reply: ``payload``
    is any buffer (bytes / bytearray / memoryview — typically a window
    into a shm segment), ``meta`` is a small msgpack-able header riding
    the RAW frame (e.g. a chunk crc), ``close`` is invoked exactly once
    after the payload has been handed to the transport (the hook that
    releases the source segment window)."""

    __slots__ = ("payload", "meta", "_close")

    def __init__(self, payload, meta=None, close: Optional[Callable[[], None]] = None):
        self.payload = payload
        self.meta = meta
        self._close = close

    def release(self) -> None:
        close, self._close = self._close, None
        if close is not None:
            try:
                close()
            except Exception:
                logger.debug("RawPayload close hook failed", exc_info=True)


class RawReply:
    """Client-side result of a call answered with a RAW frame.

    ``nbytes`` bytes were received; when the caller supplied a sink
    (``raw_into``) they were written straight into it and ``data`` is
    None; otherwise ``data`` holds the payload (the no-sink fallback —
    one materialization, same as the legacy path). ``meta`` is the
    sender's RAW header metadata (e.g. the chunk crc)."""

    __slots__ = ("nbytes", "meta", "data")

    def __init__(self, nbytes: int, meta=None, data=None):
        self.nbytes = nbytes
        self.meta = meta
        self.data = data


def _count_raw(direction: str, nbytes: int) -> None:
    from ray_tpu.observability.rpc_metrics import RAW_BYTES, RAW_FRAMES

    RAW_FRAMES.inc(labels={"direction": direction})
    RAW_BYTES.inc(nbytes, labels={"direction": direction})


def _encode_raw_header(seq: int, method: bytes, nbytes: int, meta=None) -> bytes:
    """RAW frame header body (payload travels out-of-band after it)."""
    return msgpack.packb([RAW, seq, method, nbytes, meta], use_bin_type=True)


async def _read_raw_into(reader: asyncio.StreamReader, view, length: int) -> None:
    """Receive ``length`` out-of-band payload bytes into ``view`` (a
    writable buffer of at least ``length`` bytes). Copies land directly
    in the destination; transient allocations are bounded by the
    reader's buffer granularity, never the payload size."""
    off = 0
    while off < length:
        chunk = await reader.read(min(length - off, _RAW_READ_CHUNK))
        if not chunk:
            raise asyncio.IncompleteReadError(b"", length - off)
        view[off : off + len(chunk)] = chunk
        off += len(chunk)


async def _read_raw_bytes(reader: asyncio.StreamReader, length: int) -> bytearray:
    """No-sink fallback: materialize the payload in one bytearray."""
    buf = bytearray(length)
    await _read_raw_into(reader, memoryview(buf), length)
    return buf


async def _read_raw_join(reader: asyncio.StreamReader, length: int) -> bytes:
    """Materialize the payload as ``bytes`` with ONE full-size
    allocation: join the reader's chunks directly (a bytearray +
    ``bytes()`` round-trip would pay a second full-payload copy)."""
    chunks: list = []
    off = 0
    while off < length:
        chunk = await reader.read(min(length - off, _RAW_READ_CHUNK))
        if not chunk:
            raise asyncio.IncompleteReadError(b"", length - off)
        chunks.append(chunk)
        off += len(chunk)
    return chunks[0] if len(chunks) == 1 else b"".join(chunks)


async def _drain_raw(reader: asyncio.StreamReader, length: int) -> None:
    """Discard an unwanted RAW payload, keeping the stream in sync."""
    off = 0
    while off < length:
        chunk = await reader.read(min(length - off, _RAW_READ_CHUNK))
        if not chunk:
            raise asyncio.IncompleteReadError(b"", length - off)
        off += len(chunk)


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    data = await reader.readexactly(length)
    return msgpack.unpackb(data, raw=True, use_list=True)


def _iter_messages(msg):
    """Expand one wire frame into its logical messages: a BATCH frame's
    payload slot is the FIFO list of packed sub-frame bodies; anything
    else is itself. Batches never nest."""
    if msg[0] != BATCH:
        yield msg
        return
    for body in msg[3]:
        yield msgpack.unpackb(body, raw=True, use_list=True)


def _encode_body(
    kind: int, seq: int, method: bytes, payload: bytes, meta=None
) -> bytes:
    """A frame body WITHOUT the length prefix (the unit of batching).
    ``meta`` (requests only) is the dedup stamp ``[client_id,
    request_id]``; 4-slot frames remain valid on the wire."""
    if meta is None:
        return msgpack.packb([kind, seq, method, payload], use_bin_type=True)
    return msgpack.packb([kind, seq, method, payload, meta], use_bin_type=True)


def _encode_frame(kind: int, seq: int, method: bytes, payload: bytes) -> bytes:
    body = _encode_body(kind, seq, method, payload)
    return _LEN.pack(len(body)) + body


def _wire_from_bodies(bodies: list) -> bytes:
    """Serialize a FIFO list of frame bodies for one send: consecutive
    bodies coalesce into BATCH frames up to ``rpc_batch_max_frames`` /
    ``rpc_batch_max_bytes``; singletons travel as plain frames. Order on
    the wire is exactly the queue order, so per-connection FIFO holds."""
    max_frames = GLOBAL_CONFIG.rpc_batch_max_frames
    max_bytes = GLOBAL_CONFIG.rpc_batch_max_bytes
    if len(bodies) == 1 or max_frames <= 1:
        return b"".join(_LEN.pack(len(b)) + b for b in bodies)
    out: list = []
    group: list = []
    group_bytes = 0

    def close():
        nonlocal group, group_bytes
        if not group:
            return
        if len(group) == 1:
            body = group[0]
        else:
            body = msgpack.packb([BATCH, 0, b"", group], use_bin_type=True)
        out.append(_LEN.pack(len(body)))
        out.append(body)
        group = []
        group_bytes = 0

    for body in bodies:
        if group and (
            len(group) >= max_frames or group_bytes + len(body) > max_bytes
        ):
            close()
        group.append(body)
        group_bytes += len(body)
    close()
    return b"".join(out)


class RpcServer:
    """Async RPC server. Handlers: ``async def h(payload, ctx) -> result``.

    ``ctx`` is the per-connection ``ServerConnection`` — handlers use it to
    register push subscriptions or learn the peer.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[bytes, Callable[[Any, "ServerConnection"], Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self.on_disconnect: Optional[Callable[["ServerConnection"], None]] = None
        # request dedup / reply cache (exactly-once-effective mutating
        # RPCs): SERVER-level, not per-connection — a retry after a
        # reconnect must still find the original execution's reply.
        self._dedup_done: "OrderedDict[Tuple[bytes, int], Tuple[int, bytes]]" = OrderedDict()
        self._dedup_bytes = 0
        self._dedup_inflight: Dict[Tuple[bytes, int], asyncio.Future] = {}
        #: optional fencing gate ``(method_name, epoch) -> Optional[
        #: Exception]`` consulted for requests stamped with a controller
        #: incarnation epoch (meta slot 3). Daemons install one that
        #: tracks the highest epoch seen and rejects lower-epoch writes
        #: with StaleControllerError — see core/node_daemon.py.
        self.epoch_gate: Optional[Callable[[str, int], Optional[Exception]]] = None

    def register(self, method: str, handler) -> None:
        self._handlers[method.encode()] = handler

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port,
            limit=GLOBAL_CONFIG.rpc_stream_buffer_bytes,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        # handler timing registry (reference event_stats.h): every dispatch
        # below records queueing + run latency under the method name
        from ray_tpu.observability.event_stats import GLOBAL_EVENT_STATS

        GLOBAL_EVENT_STATS.ensure_metrics()
        return self.port

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        _tune_transport(writer)
        conn = ServerConnection(reader, writer)
        self._conns.add(conn)
        try:
            while True:
                try:
                    msg = await _read_frame(reader)
                    if msg[0] == RAW:
                        # clients don't send RAW requests today; drain the
                        # out-of-band payload so the stream stays in sync
                        await _drain_raw(reader, msg[3])
                        continue
                except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
                    break
                # a BATCH frame dispatches all its requests from this ONE
                # read wakeup, in queue order (micro-batching)
                enqueued_at = time.monotonic()
                for m in _iter_messages(msg):
                    if m[0] != REQUEST:
                        continue
                    asyncio.ensure_future(
                        self._dispatch(
                            conn, m[1], m[2], m[3], enqueued_at,
                            m[4] if len(m) > 4 else None,
                        )
                    )
        finally:
            self._conns.discard(conn)
            conn._closed = True
            if self.on_disconnect:
                try:
                    self.on_disconnect(conn)
                except Exception:
                    logger.exception("on_disconnect callback failed")
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(
        self,
        conn: "ServerConnection",
        seq: int,
        method: bytes,
        payload: bytes,
        enqueued_at: float = 0.0,
        meta=None,
    ):
        from ray_tpu.observability.event_stats import GLOBAL_EVENT_STATS

        handler = self._handlers.get(method)
        started_at = time.monotonic()
        try:
            if handler is None:
                raise RpcError(f"no handler for {method.decode()!r}")
            method_name = method.decode()
            fault = _next_fault(method_name)
            reply_drop = False
            if fault is not None:
                mode, param = fault
                _count_injection(mode)
                if mode == "request_drop":
                    # the request "never arrived": no handler, no dedup
                    # record — a retry is trivially safe
                    raise ChaosInjectedError(
                        f"chaos: injected failure for {method_name}"
                    )
                if mode == "disconnect":
                    # hard connection reset mid-call: nothing travels
                    # back; the client's read loop fails its pending
                    # calls with ConnectionLost and reconnects
                    conn.abort()
                    return
                if mode == "delay":
                    await asyncio.sleep(param)
                elif mode == "reply_drop":
                    reply_drop = True
            # --- request dedup (exactly-once-effective) ---------------
            # meta slots: [client_id, request_id, trace_ctx?]. A zero
            # request id is the "trace only, no dedup" sentinel (real
            # ids start at 1) — idempotent methods under an active
            # trace still carry the context without entering the cache.
            trace_wire = meta[2] if meta is not None and len(meta) > 2 else None
            # --- epoch fencing (meta slot 3) --------------------------
            # Only controllers stamp an incarnation epoch, so a present
            # epoch + an installed gate means "controller-originated
            # write": the gate records the highest epoch seen and
            # rejects lower ones BEFORE dedup/execution — a deposed
            # controller's write must not execute OR consume a dedup
            # slot.
            wire_epoch = meta[3] if meta is not None and len(meta) > 3 else None
            if wire_epoch is not None and self.epoch_gate is not None:
                gate_err = self.epoch_gate(method_name, wire_epoch)
                if gate_err is not None:
                    raise gate_err
            dedup_key = None
            if meta is not None and meta[1]:
                dedup_key = (bytes(meta[0]), meta[1])
                record = self._dedup_done.get(dedup_key)
                if record is None:
                    inflight = self._dedup_inflight.get(dedup_key)
                    if inflight is not None:
                        # the original execution is still running: wait
                        # for ITS outcome instead of executing again
                        try:
                            record = await asyncio.shield(inflight)
                        except BaseException:
                            raise RpcError(
                                "duplicate request raced a cancelled execution"
                            )
                if record is not None:
                    self._count_dedup_hit(method_name)
                    if reply_drop:
                        raise ChaosInjectedError(
                            f"chaos: reply dropped for {method_name} (dedup hit)"
                        )
                    await conn.send(record[0], seq, method, record[1])
                    return
                fut: asyncio.Future = asyncio.get_event_loop().create_future()
                self._dedup_inflight[dedup_key] = fut
            # --- execute ----------------------------------------------
            raw_result: Optional[RawPayload] = None
            # expose the dedup key to the handler (this dispatch runs in
            # its own task, so the set is task-local): the controller
            # WAL journals it with the mutation for replay re-seeding
            _dedup_token = _CURRENT_DEDUP_KEY.set(dedup_key)
            try:
                try:
                    arg = pickle.loads(payload) if payload else None
                    if trace_wire:
                        # sampled caller: run the handler inside its
                        # trace so server-side spans (and nested calls)
                        # parent to the sender's span
                        with _tracing.scope(trace_wire), _tracing.span(
                            f"rpc::{method_name}", "rpc"
                        ):
                            result = await handler(arg, conn)
                    else:
                        result = await handler(arg, conn)
                    if isinstance(result, RawPayload):
                        # zero-copy bulk reply: travels as a RAW frame and
                        # NEVER enters the dedup reply cache (one multi-MiB
                        # chunk would evict the whole 32 MiB control-plane
                        # window) — bulk methods are idempotent reads, so a
                        # post-eviction retry safely re-executes
                        raw_result = result
                        record = (
                            REPLY_ERR,
                            pickle.dumps(
                                RpcError(
                                    f"raw reply for {method_name} is not "
                                    "cacheable; retry the call"
                                )
                            ),
                        )
                    else:
                        record = (REPLY_OK, pickle.dumps(result, protocol=5))
                except Exception as e:  # noqa: BLE001 — reply with the error
                    # the handler RAN (or its arguments were undecodable):
                    # the error IS the outcome, and a retry must get the
                    # same answer, not a second execution
                    record = (REPLY_ERR, pickle.dumps(e))
                if dedup_key is not None and raw_result is None:
                    self._dedup_record(dedup_key, record)
                elif dedup_key is not None:
                    # resolve duplicate waiters with the retryable error
                    # WITHOUT caching (raw replies are dedup-exempt)
                    fut = self._dedup_inflight.pop(dedup_key, None)
                    if fut is not None and not fut.done():
                        fut.set_result(record)
            finally:
                _CURRENT_DEDUP_KEY.reset(_dedup_token)
                # a cancelled execution (server stopping) must not leave
                # duplicate waiters parked on a future nobody resolves
                if dedup_key is not None:
                    stale = self._dedup_inflight.pop(dedup_key, None)
                    if stale is not None and not stale.done():
                        stale.cancel()
            if reply_drop:
                # the handler executed and its reply is cached — the lost
                # reply is exactly the duplicate-execution trap; the
                # client's retry must come back through the dedup path
                if raw_result is not None:
                    raw_result.release()
                raise ChaosInjectedError(
                    f"chaos: reply dropped for {method_name} after execution"
                )
            if raw_result is not None:
                await conn.send_raw(seq, method, raw_result)
            else:
                await conn.send(record[0], seq, method, record[1])
        except Exception as e:  # noqa: BLE001 — reply with the error
            try:
                await conn.send(REPLY_ERR, seq, method, pickle.dumps(e))
            except Exception:
                logger.debug("failed to send error reply", exc_info=True)
        finally:
            GLOBAL_EVENT_STATS.record(
                method.decode(errors="replace"),
                started_at - enqueued_at if enqueued_at else 0.0,
                time.monotonic() - started_at,
            )

    def seed_dedup(self, key: Tuple[bytes, int], record: Tuple[int, bytes]) -> None:
        """Pre-populate the reply cache (controller WAL replay): a
        client retrying a mutation it acked against the PREVIOUS
        incarnation gets the journaled reply instead of a second
        execution — exactly-once survives failover."""
        self._dedup_record(key, record)

    def _dedup_record(self, key: Tuple[bytes, int], record: Tuple[int, bytes]) -> None:
        """Resolve duplicate waiters and cache the reply, bounded by the
        entry/byte caps with oldest-first eviction."""
        fut = self._dedup_inflight.pop(key, None)
        if fut is not None and not fut.done():
            fut.set_result(record)
        self._dedup_done[key] = record
        self._dedup_bytes += len(record[1])
        max_entries = GLOBAL_CONFIG.rpc_dedup_cache_entries
        max_bytes = GLOBAL_CONFIG.rpc_dedup_cache_max_bytes
        while self._dedup_done and (
            len(self._dedup_done) > max_entries or self._dedup_bytes > max_bytes
        ):
            _, old = self._dedup_done.popitem(last=False)
            self._dedup_bytes -= len(old[1])

    @staticmethod
    def _count_dedup_hit(method_name: str) -> None:
        from ray_tpu.observability.rpc_metrics import RPC_DEDUP_HITS

        RPC_DEDUP_HITS.inc(labels={"method": method_name})

    async def stop(self) -> None:
        # Close live connections first: in py3.12 ``wait_closed`` waits for
        # all of them, so the order matters.
        for conn in list(self._conns):
            conn._closed = True
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass


class ServerConnection:
    """Server side of one client connection; supports push messages.

    Writes are CORKED: frames buffer per connection and flush once per
    loop tick, coalescing replies into one send syscall (syscalls cost
    ~100µs on virtualized hosts — per-reply writes dominated the task
    round-trip before batching)."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self._closed = False
        self._out: list = []
        self._flush_scheduled = False
        self.peer_tags: Dict[str, Any] = {}  # handlers stash identity here

    async def send(self, kind: int, seq: int, method: bytes, payload: bytes) -> None:
        if self._closed:
            raise ConnectionLost("connection closed")
        body = _encode_body(kind, seq, method, payload)
        self._enqueue(body, len(body))
        await self.writer.drain()

    async def send_raw(self, seq: int, method: bytes, raw: RawPayload) -> None:
        """Queue a RAW frame: header body + out-of-band payload, written
        back to back at flush time (scatter-gather — the payload goes to
        the transport straight from its source buffer, no concatenation
        copy). ``raw.release()`` runs once the transport has consumed
        the buffer."""
        if self._closed:
            raw.release()
            raise ConnectionLost("connection closed")
        nbytes = len(raw.payload)
        header = _encode_raw_header(seq, method, nbytes, raw.meta)
        self._enqueue((header, raw), len(header) + nbytes)
        _count_raw("sent", nbytes)
        await self.writer.drain()

    async def push_raw(self, channel: int, envelope: Dict[str, Any], payload) -> None:
        """Server-initiated RAW push: the bulk ``payload`` travels out of
        band; the receiver reassembles ``envelope["data"] = payload`` and
        hands the dict to the channel's push handler — same handler
        contract as a plain :meth:`push`, minus the bulk pickle/msgpack
        copies (the streaming-generator item transport)."""
        meta = pickle.dumps(envelope, protocol=5)
        await self.send_raw(channel, b"", RawPayload(payload, meta=meta))

    def _enqueue(self, entry, nbytes: int) -> None:
        self._out.append(entry)
        self._out_bytes = getattr(self, "_out_bytes", 0) + nbytes
        if self._out_bytes >= _FLUSH_BYTES:
            # large buffers flush NOW: the cork trades one loop tick of
            # latency for syscall coalescing, but drain()'s flow control
            # only sees written bytes — an unbounded cork defeats it
            self._flush()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if not self._out or self._closed:
            self._drop_buffered()
            return
        bodies, self._out = self._out, []
        self._out_bytes = 0
        try:
            # consecutive plain frames coalesce into batch frames (one
            # peer read wakeup per flush); RAW entries break the run and
            # write header + payload back to back — FIFO order holds
            # across both kinds
            run: list = []
            for entry in bodies:
                if isinstance(entry, bytes):
                    run.append(entry)
                    continue
                if run:
                    self.writer.write(_wire_from_bodies(run))
                    run = []
                header, raw = entry
                self.writer.write(_LEN.pack(len(header)) + header)
                try:
                    if len(raw.payload):
                        # straight from the source buffer: the transport
                        # either sends now or keeps the unsent tail
                        self.writer.write(raw.payload)
                finally:
                    self._release_when_flushed(raw)
            if run:
                self.writer.write(_wire_from_bodies(run))
        except Exception:
            # mark closed so subsequent sends fail fast instead of
            # buffering into a dead socket until the reader notices
            self._closed = True
            for entry in bodies:  # release() is idempotent
                if not isinstance(entry, bytes):
                    entry[1].release()
            self._drop_buffered()

    def _release_when_flushed(self, raw: RawPayload) -> None:
        """Release a RAW payload's source buffer once the transport can
        no longer reference it. CPython < 3.12 selector transports COPY
        any unsent tail into their own buffer, so releasing right after
        ``write`` is safe; 3.12+ implements zero-copy writes (the
        transport queues the ORIGINAL buffer object), so defer the
        release until the write buffer has fully drained — releasing a
        queued memoryview would fatally abort the connection mid-send."""
        if sys.version_info < (3, 12) or self._closed:
            raw.release()
            return
        try:
            pending = self.writer.transport.get_write_buffer_size()
        except Exception:
            pending = 0
        if pending == 0:
            raw.release()
            return
        asyncio.get_event_loop().call_later(
            0.02, self._release_when_flushed, raw
        )

    def _drop_buffered(self) -> None:
        for entry in self._out:
            if not isinstance(entry, bytes):
                entry[1].release()
        self._out = []
        self._out_bytes = 0

    async def push(self, channel: int, payload: Any) -> None:
        """Server-initiated message on a subscription channel."""
        await self.send(PUSH, channel, b"", pickle.dumps(payload, protocol=5))

    def abort(self) -> None:
        """Hard connection reset (chaos DISCONNECT): drop buffered
        output and kill the transport without a FIN handshake, so the
        peer sees a mid-call reset."""
        self._closed = True
        self._drop_buffered()
        try:
            self.writer.transport.abort()
        except Exception:
            try:
                self.writer.close()
            except Exception:
                pass

    @property
    def closed(self) -> bool:
        return self._closed


class RpcClient:
    """Retrying client (reference retryable gRPC client): reconnects
    with jittered exponential backoff capped by the ambient
    ``core/deadline`` budget. Mutating methods (anything not in
    :data:`IDEMPOTENT_METHODS`) are stamped with a (client id, request
    id) pair held stable across retries, so a retried call lands in the
    server's reply cache instead of re-executing — see the module
    docstring. ``default_retries`` makes a client (e.g. the controller
    client) retry-by-default without touching every call site."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        name: str = "",
        default_retries: int = 0,
        role: Optional[str] = None,
    ):
        self.host = host
        self.port = port
        self.name = name or f"{host}:{port}"
        self.default_retries = default_retries
        #: role of the SERVER this client talks to ("controller" /
        #: "noded" / "worker"): selects which per-role idempotent-method
        #: set skips dedup stamping. None (untagged) uses the legacy
        #: union — see IDEMPOTENT_METHODS.
        self.role = role
        #: stable identity for the server's dedup cache; survives
        #: reconnects of this client object (a NEW client = a new
        #: logical caller = correctly never dedups against the old one)
        self.client_id = os.urandom(12)
        #: invoked (as a task) after every RE-connect — the hook for
        #: re-subscribing push channels / replaying session state
        self.on_reconnect: Optional[Callable[[], Awaitable[Any]]] = None
        #: controller incarnation epoch stamped on every outgoing call
        #: (meta slot 3). Set ONLY on clients owned by a controller —
        #: receivers with an installed ``epoch_gate`` fence stale ones.
        self.fencing_epoch: Optional[int] = None
        self._ever_connected = False
        self._reader = None
        self._writer = None
        self._seq = 0
        self._rid = 0
        self._pending: Dict[int, asyncio.Future] = {}
        #: seq -> caller-provided writable buffer for RAW replies
        #: (``call(raw_into=...)``); reset with ``_pending`` per
        #: connection, entries popped when the reply arrives
        self._raw_sinks: Dict[int, Any] = {}
        self._push_handlers: Dict[int, Callable[[Any], None]] = {}
        self._conn_lock: Optional[asyncio.Lock] = None
        #: monotonic stamp of the last FAILED connect attempt: callers
        #: already parked on the lock while it ran fail together instead
        #: of serially re-running the full connect-timeout loop each
        self._last_connect_failure = float("-inf")
        self._read_task: Optional[asyncio.Task] = None
        self._closed = False
        # write cork (see ServerConnection): frames issued in one loop
        # tick coalesce into a single send syscall
        self._out: list = []
        self._flush_scheduled = False

    def next_request_id(self) -> int:
        """Pre-allocate a dedup request id (io-loop only). Callers that
        manage their own retry loops (actor task submission) pass it to
        ``call(request_id=...)`` so every re-push of the same logical
        operation shares one server-side dedup slot."""
        self._rid += 1
        return self._rid

    async def _ensure_connected(self, connect_timeout: Optional[float] = None):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        reconnected = False
        entered = time.monotonic()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            if self._last_connect_failure >= entered:
                # a connect attempt that spanned our ENTIRE wait just
                # exhausted its timeout against this address — fail
                # together. Without this, N concurrent calls to a dead
                # peer serialize behind the lock and pay N x the connect
                # timeout (a dead object-transfer source made ten
                # concurrent pulls crawl through ~10s probes one by
                # one). A call arriving AFTER the failure still gets a
                # full fresh attempt — the peer may be back.
                raise ConnectionLost(f"cannot connect to {self.name}")
            from ray_tpu.core.deadline import effective_timeout

            budget = effective_timeout(
                connect_timeout if connect_timeout is not None else GLOBAL_CONFIG.rpc_connect_timeout_s
            )
            deadline = time.monotonic() + (budget if budget is not None else GLOBAL_CONFIG.rpc_connect_timeout_s)
            delay = GLOBAL_CONFIG.rpc_retry_base_delay_s
            while True:
                try:
                    self._reader, self._writer = await asyncio.open_connection(
                        self.host, self.port,
                        limit=GLOBAL_CONFIG.rpc_stream_buffer_bytes,
                    )
                    _tune_transport(self._writer)
                    break
                except OSError:
                    if time.monotonic() > deadline or self._closed:
                        self._last_connect_failure = time.monotonic()
                        raise ConnectionLost(f"cannot connect to {self.name}")
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, GLOBAL_CONFIG.rpc_retry_max_delay_s)
            if self._read_task is not None:
                self._read_task.cancel()
            # Fresh pending map per connection: a stale read loop's cleanup
            # must never fail calls issued on a newer connection.
            self._pending = {}
            self._raw_sinks = {}
            self._read_task = asyncio.ensure_future(
                self._read_loop(
                    self._reader, self._writer, self._pending, self._raw_sinks
                )
            )
            reconnected = self._ever_connected
            self._ever_connected = True
        if reconnected and self.on_reconnect is not None and not self._closed:
            # outside the lock (the hook's own calls re-enter it); as a
            # task so the triggering call proceeds — pushes missed in
            # the hook's in-flight window are the same gap any
            # reconnect has, and the hook's replay covers it
            asyncio.ensure_future(self._run_reconnect_hook())

    async def _run_reconnect_hook(self) -> None:
        try:
            await self.on_reconnect()
        except Exception:
            logger.warning(
                "on_reconnect hook for %s failed", self.name, exc_info=True
            )

    async def _read_loop(self, reader, writer, pending, raw_sinks):
        try:
            while True:
                msg = await _read_frame(reader)
                if msg[0] == RAW:
                    # out-of-band payload follows the header on the
                    # stream: consume it before the next frame
                    await self._handle_raw(reader, msg, pending, raw_sinks)
                    continue
                for m in _iter_messages(msg):
                    kind, seq, method, payload = m[0], m[1], m[2], m[3]
                    if kind == PUSH:
                        handler = self._push_handlers.get(seq)
                        if handler is not None:
                            try:
                                handler(pickle.loads(payload))
                            except Exception:
                                logger.exception("push handler failed")
                        continue
                    fut = pending.pop(seq, None)
                    raw_sinks.pop(seq, None)
                    if fut is None or fut.done():
                        continue
                    if kind == REPLY_OK:
                        fut.set_result(pickle.loads(payload))
                    else:
                        fut.set_exception(pickle.loads(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"connection to {self.name} lost"))
            pending.clear()
            raw_sinks.clear()
            try:
                writer.close()
            except Exception:
                pass
            if self._writer is writer:
                self._writer = None

    async def _handle_raw(self, reader, msg, pending, raw_sinks) -> None:
        """One RAW frame: a reply (non-empty method, seq matches a
        pending call — received straight into the caller's sink when one
        was registered) or a push (empty method, seq is the channel —
        the pickled envelope in meta gets ``data`` reassembled)."""
        _kind, seq, method, length, meta = (
            msg[0], msg[1], msg[2], msg[3],
            msg[4] if len(msg) > 4 else None,
        )
        if length > MAX_FRAME:
            raise RpcError(f"raw payload too large: {length}")
        if not method:
            # RAW push: envelope dict + out-of-band bulk data
            data = await _read_raw_join(reader, length)
            _count_raw("received", length)
            handler = self._push_handlers.get(seq)
            if handler is not None:
                try:
                    envelope = pickle.loads(meta) if meta else {}
                    envelope["data"] = data
                    handler(envelope)
                except Exception:
                    logger.exception("raw push handler failed")
            return
        fut = pending.pop(seq, None)
        sink = raw_sinks.pop(seq, None)
        if fut is None or fut.done():
            # late reply (caller timed out / retried): NEVER touch the
            # caller's buffer — a retry may be rewriting the same range
            await _drain_raw(reader, length)
            return
        if sink is not None and length <= len(sink):
            await _read_raw_into(reader, sink, length)
            _count_raw("received", length)
            if not fut.done():
                fut.set_result(RawReply(length, meta))
            return
        # no sink (plain call answered raw) or an undersized one:
        # materialize — the caller still gets the payload, minus the
        # zero-copy property
        data = await _read_raw_bytes(reader, length)
        _count_raw("received", length)
        if not fut.done():
            fut.set_result(RawReply(length, meta, data))

    def subscribe_push(self, channel: int, handler: Callable[[Any], None]) -> None:
        self._push_handlers[channel] = handler

    async def call(
        self,
        method: str,
        payload: Any = None,
        *,
        timeout: Optional[float] = None,
        retries: Optional[int] = None,
        connect_timeout: Optional[float] = None,
        request_id: Optional[int] = None,
        dedup: Optional[bool] = None,
        raw_into=None,
    ):
        """One logical RPC with retry-until-done semantics.

        * ``raw_into``: a writable buffer (memoryview) for a RAW reply —
          the server's out-of-band payload is received straight into it
          and the call resolves to a :class:`RawReply` (``data is None``
          when the sink was used). A server answering with a plain reply
          resolves normally; callers handle both shapes.

        * ``retries``: transport-failure retry budget; None = this
          client's ``default_retries``. ``timeout`` bounds each attempt.
        * ``request_id``/``dedup``: every retry of one ``call()``
          carries the SAME request id for dedup-required methods, so the
          server answers a post-execution retry from its reply cache
          instead of re-executing. Pass ``request_id`` (from
          :meth:`next_request_id`) to extend that guarantee across a
          caller-managed retry loop; ``dedup=False`` opts a call out.
        * Chaos-injected faults are retried with a short flat sleep on a
          separate generous budget (the server is alive by construction)
          — a caller with ``retries=0`` still survives sub-certain
          injection probabilities, matching the old pre-handler chaos
          contract while the dedup cache keeps mutating retries safe.
        * Backoff is jittered-exponential and, like the retry loop
          itself, capped by the ambient ``core/deadline`` budget: an
          expired budget raises the last failure instead of sleeping.
        """
        from ray_tpu.core.deadline import current_deadline

        if retries is None:
            retries = self.default_retries
        if dedup is None:
            dedup = (
                GLOBAL_CONFIG.rpc_dedup_enabled
                and method not in idempotent_methods(self.role)
            )
        rid = request_id
        if rid is None and dedup:
            rid = self.next_request_id()
        ambient = current_deadline()
        attempt = 0
        chaos_attempts = 0
        delay = GLOBAL_CONFIG.rpc_retry_base_delay_s
        while True:
            try:
                return await self._call_once(
                    method, payload, timeout, connect_timeout,
                    rid if dedup else None, raw_into,
                )
            except ChaosInjectedError as e:
                chaos_attempts += 1
                if chaos_attempts > max(retries, _CHAOS_RETRY_CAP) or self._closed:
                    raise
                last_err: Exception = e
                sleep_s = _CHAOS_RETRY_SLEEP_S * (0.5 + random.random())
            except (ConnectionLost, asyncio.TimeoutError) as e:
                attempt += 1
                if attempt > retries or self._closed:
                    raise
                last_err = e
                sleep_s = delay * (0.5 + random.random() * 0.5)  # jitter
                delay = min(delay * 2, GLOBAL_CONFIG.rpc_retry_max_delay_s)
            self._count_retry(method)
            if ambient is not None:
                remaining = ambient.remaining()
                if remaining <= 0:
                    raise last_err  # budget exhausted: surface the failure
                sleep_s = min(sleep_s, remaining)
            await asyncio.sleep(sleep_s)

    @staticmethod
    def _count_retry(method: str) -> None:
        from ray_tpu.observability.rpc_metrics import RPC_RETRIES

        RPC_RETRIES.inc(labels={"method": method})

    async def _call_once(
        self,
        method: str,
        payload: Any,
        timeout: Optional[float],
        connect_timeout: Optional[float] = None,
        request_id: Optional[int] = None,
        raw_into=None,
    ):
        await self._ensure_connected(connect_timeout)
        self._seq += 1
        seq = self._seq
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[seq] = fut
        if raw_into is not None:
            self._raw_sinks[seq] = raw_into
        try:
            # meta = [client_id, request_id, trace_ctx?, epoch?]:
            # request_id 0 is the trace-only sentinel (no dedup);
            # untraced/unfenced calls without a request id stay
            # meta-less — the common wire format is byte-identical to
            # before tracing/fencing existed. The fencing epoch (set
            # only on controller-owned clients) rides slot 3, padding
            # the trace slot with None when untraced.
            trace = _tracing.current_wire()
            epoch = self.fencing_epoch
            if request_id is None and trace is None and epoch is None:
                meta = None
            else:
                meta = [self.client_id, request_id or 0]
                if trace is not None or epoch is not None:
                    meta.append(list(trace) if trace is not None else None)
                if epoch is not None:
                    meta.append(epoch)
            body = _encode_body(
                REQUEST,
                seq,
                method.encode(),
                pickle.dumps(payload, protocol=5),
                meta,
            )
            self._out.append(body)
            self._out_bytes = getattr(self, "_out_bytes", 0) + len(body)
            if self._out_bytes >= _FLUSH_BYTES:
                self._flush()  # see ServerConnection.send: bound the cork
            elif not self._flush_scheduled:
                self._flush_scheduled = True
                asyncio.get_event_loop().call_soon(self._flush)
            await self._writer.drain()
        except (ConnectionResetError, BrokenPipeError, AttributeError) as e:
            self._pending.pop(seq, None)
            self._raw_sinks.pop(seq, None)
            raise ConnectionLost(str(e))
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def _flush(self) -> None:
        self._flush_scheduled = False
        writer = self._writer
        if not self._out or writer is None:
            self._out.clear()
            self._out_bytes = 0
            return
        bodies, self._out = self._out, []
        self._out_bytes = 0
        try:
            # one write, frames coalesced into batch frames (micro-batching)
            writer.write(_wire_from_bodies(bodies))
        except Exception:
            # fail in-flight calls NOW — waiting for the read loop to
            # notice the dead socket can add a full timeout of latency
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"write to {self.name} failed"))
            self._pending.clear()
            self._raw_sinks.clear()
            try:
                writer.close()
            except Exception:
                pass
            if self._writer is writer:
                self._writer = None

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class IoThread:
    """A dedicated thread running an asyncio loop; the per-process event
    loop that all RPC clients/servers of a (sync) process live on.

    Reference analogue: the per-process asio io_context with instrumented
    handlers (``common/event_stats.h``)."""

    def __init__(self, name: str = "ray-tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.monitor = None  # LoopMonitor (stall watchdog), set in _run
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        from concurrent.futures import ThreadPoolExecutor

        asyncio.set_event_loop(self.loop)
        # Long-poll handlers park in the default executor; the stock pool
        # (cpu+4 threads) is far too small under many concurrent waiters.
        self.loop.set_default_executor(ThreadPoolExecutor(max_workers=64, thread_name_prefix="io-exec"))
        # Stall watchdog (hang defense): a handler blocking THIS loop is
        # invisible from outside — the monitor's heartbeat + off-loop
        # watchdog turns "process frozen" into a named stack dump.
        from ray_tpu.observability.event_stats import install_loop_monitor

        self.monitor = install_loop_monitor(self.loop, self._thread.name)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the io loop from a sync context. The
        caller thread's ambient trace (if any) is re-entered around the
        coroutine — run_coroutine_threadsafe does not carry contextvars,
        and RPCs issued for a traced request must stamp its context."""
        wire = _tracing.current_wire()
        if wire is not None:
            coro = _tracing.carry(coro, wire)
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def post(self, coro) -> None:
        asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        # detach the watchdog FIRST: a stopping loop's silent heartbeat
        # must not be reported (or worse, aborted) as a stall
        from ray_tpu.observability.event_stats import remove_loop_monitor

        remove_loop_monitor(self.loop)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)
