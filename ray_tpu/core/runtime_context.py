"""Runtime context (cf. reference ``ray.runtime_context.RuntimeContext``)."""

from __future__ import annotations

from typing import Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        return self._worker.current_task_id.hex()

    def get_actor_id(self) -> Optional[str]:
        """Hex id of the actor this process hosts (None outside actors).
        Set by the executor at actor creation (task_executor)."""
        aid = getattr(self._worker, "current_actor_id", None)
        return aid.hex() if aid is not None else None

    def get_node_id(self) -> Optional[str]:
        addr = self._worker.address
        return addr.node_id.hex() if addr else None

    @property
    def namespace(self) -> str:
        return self._worker.namespace

    def get_assigned_resources(self):
        return getattr(self._worker, "assigned_resources", {})
