"""Cluster scheduling policies.

Reference: ``src/ray/raylet/scheduling/`` — hybrid local-first/top-k policy
(``hybrid_scheduling_policy.h:50``), spread, node-affinity, node-label
(``composite_scheduling_policy.h:33``) and the bundle placement policies
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD
(``bundle_scheduling_policy.h:82-106``). Policies here are pure functions
over the synced cluster view (plain dicts) so both the controller (actor +
PG placement) and node daemons (task spillback) share them.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ray_tpu.core.task_spec import (
    DefaultScheduling,
    NodeAffinityScheduling,
    NodeLabelScheduling,
    PlacementGroupScheduling,
    SchedulingStrategy,
    SpreadScheduling,
)


def fits(available: Dict[str, float], request: Dict[str, float]) -> bool:
    return all(available.get(k, 0.0) + 1e-9 >= v for k, v in request.items())


def utilization(total: Dict[str, float], available: Dict[str, float]) -> float:
    """LeastResourceScorer (``scorer.h:41``): max over resources of
    used/total."""
    worst = 0.0
    for k, t in total.items():
        if t <= 0:
            continue
        worst = max(worst, (t - available.get(k, 0.0)) / t)
    return worst


@dataclass
class BundleReservation:
    node_id: bytes
    bundle_index: int
    resources: Dict[str, float]


def pick_node_hybrid(
    nodes: Sequence,  # NodeInfo-like: .node_id .total .available .labels
    request: Dict[str, float],
    strategy: SchedulingStrategy,
    pgs: Optional[Dict[bytes, object]] = None,
    local_node_id: Optional[bytes] = None,
    spread_threshold: float = 0.5,
):
    """Pick a node for one task/actor. Returns the node object or None."""
    if isinstance(strategy, NodeAffinityScheduling):
        for n in nodes:
            if n.node_id == strategy.node_id:
                if fits(n.available, request):
                    return n
                # soft affinity: target full → fall back to any other fit
                return _best_fit(nodes, request) if strategy.soft else None
        return _best_fit(nodes, request) if strategy.soft else None

    if isinstance(strategy, PlacementGroupScheduling) and pgs is not None:
        pg = pgs.get(strategy.pg_id)
        if pg is None or not getattr(pg, "reservations", None):
            return None
        node_ids = {r.bundle_index: r.node_id for r in pg.reservations}
        if strategy.bundle_index >= 0:
            target = node_ids.get(strategy.bundle_index)
        else:
            target = None
            for r in pg.reservations:
                target = r.node_id
                break
        for n in nodes:
            if n.node_id == target:
                return n
        return None

    if isinstance(strategy, NodeLabelScheduling):
        def match(n, conditions):
            return all(n.labels.get(k) in vals for k, vals in conditions)

        hard = [n for n in nodes if match(n, strategy.hard) and fits(n.available, request)]
        if hard:
            soft = [n for n in hard if match(n, strategy.soft)]
            return random.choice(soft or hard)
        return None

    if isinstance(strategy, SpreadScheduling):
        candidates = [n for n in nodes if fits(n.available, request)]
        if not candidates:
            return None
        return min(candidates, key=lambda n: (utilization(n.total, n.available), random.random()))

    # Default hybrid: prefer the local node while its utilization is below
    # the threshold, else the best (least-utilized) remote fit
    # (``hybrid_scheduling_policy.h:50``).
    if local_node_id is not None:
        local = next((n for n in nodes if n.node_id == local_node_id), None)
        if (
            local is not None
            and fits(local.available, request)
            and utilization(local.total, local.available) < spread_threshold
        ):
            return local
    return _best_fit(nodes, request)


def _best_fit(nodes: Sequence, request: Dict[str, float]):
    candidates = [n for n in nodes if fits(n.available, request)]
    if not candidates:
        return None
    candidates.sort(key=lambda n: utilization(n.total, n.available))
    # top-k jitter to avoid thundering herds (reference top-k fraction)
    k = max(1, len(candidates) // 5)
    return random.choice(candidates[:k])


def feasible_anywhere(nodes: Sequence, request: Dict[str, float]) -> bool:
    return any(fits(n.total, request) for n in nodes)


def place_bundles(
    nodes: Sequence, bundles: List[Dict[str, float]], strategy: str
) -> Optional[List[BundleReservation]]:
    """Plan bundle→node placement (``bundle_scheduling_policy.h:82-106``).

    Returns None if infeasible right now. Pure planning — reservation
    happens via 2PC with the daemons afterwards.
    """
    avail = {n.node_id: dict(n.available) for n in nodes}
    nodes_by_id = {n.node_id: n for n in nodes}

    def take(node_id: bytes, req: Dict[str, float]) -> bool:
        a = avail[node_id]
        if not fits(a, req):
            return False
        for k, v in req.items():
            a[k] = a.get(k, 0.0) - v
        return True

    plan: List[BundleReservation] = []

    if strategy == "STRICT_PACK":
        for node_id in avail:
            trial = dict(avail[node_id])
            ok = True
            for b in bundles:
                if not fits(trial, b):
                    ok = False
                    break
                for k, v in b.items():
                    trial[k] = trial.get(k, 0.0) - v
            if ok:
                return [
                    BundleReservation(node_id, i, dict(b)) for i, b in enumerate(bundles)
                ]
        return None

    if strategy == "STRICT_SPREAD":
        used_nodes: set = set()
        for i, b in enumerate(bundles):
            placed = False
            ranked = sorted(
                (nid for nid in avail if nid not in used_nodes),
                key=lambda nid: utilization(nodes_by_id[nid].total, avail[nid]),
            )
            for nid in ranked:
                if take(nid, b):
                    plan.append(BundleReservation(nid, i, dict(b)))
                    used_nodes.add(nid)
                    placed = True
                    break
            if not placed:
                return None
        return plan

    if strategy == "SPREAD":
        for i, b in enumerate(bundles):
            ranked = sorted(
                avail,
                key=lambda nid: (
                    sum(1 for r in plan if r.node_id == nid),
                    utilization(nodes_by_id[nid].total, avail[nid]),
                ),
            )
            placed = False
            for nid in ranked:
                if take(nid, b):
                    plan.append(BundleReservation(nid, i, dict(b)))
                    placed = True
                    break
            if not placed:
                return None
        return plan

    # PACK (default): minimize node count — greedy fill in utilization order.
    for i, b in enumerate(bundles):
        ranked = sorted(
            avail,
            key=lambda nid: (
                -sum(1 for r in plan if r.node_id == nid),  # prefer already-used
                utilization(nodes_by_id[nid].total, avail[nid]),
            ),
        )
        placed = False
        for nid in ranked:
            if take(nid, b):
                plan.append(BundleReservation(nid, i, dict(b)))
                placed = True
                break
        if not placed:
            return None
    return plan
