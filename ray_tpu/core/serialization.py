"""Value serialization for the object store and RPC payloads.

Equivalent of the reference's ``python/ray/_private/serialization.py``:
cloudpickle for arbitrary Python objects with pickle-protocol-5 out-of-band
buffers so numpy (and host-side jax) arrays are written/read zero-copy
against shared memory. Wire format:

    [u32 nbuffers] [u64 len_meta] [meta pickle] ([u64 len_i] [buffer_i])*

``ObjectRef``s nested inside values are extracted during serialization so
the ownership layer can track borrowers (reference: ``serialization.py``
contained-object-ref accounting), and re-hydrated on deserialization.

jax.Array values are converted to numpy on serialize via ``__array__`` —
device buffers never pass through the object store in round 1; the
device-to-device path is the collective/ICI layer's job.
"""

from __future__ import annotations

import pickle
import struct
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

import cloudpickle
import numpy as np

_HEADER = struct.Struct("<IQ")
_LEN = struct.Struct("<Q")

# Registered custom (reducer, reconstructor) pairs, keyed by type.
_custom_serializers: Dict[Type, Tuple[Callable, Callable]] = {}
_lock = threading.Lock()


def register_serializer(cls: Type, *, serializer: Callable, deserializer: Callable) -> None:
    """Same contract as reference ``ray.util.register_serializer``."""
    with _lock:
        _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: Type) -> None:
    with _lock:
        _custom_serializers.pop(cls, None)


def _reconstruct_custom(cls_bytes: bytes, payload: Any) -> Any:
    cls = cloudpickle.loads(cls_bytes)
    pair = _custom_serializers.get(cls)
    if pair is None:
        raise ValueError(f"no deserializer registered for {cls}")
    return pair[1](payload)


class SerializedValue:
    """A serialized value: metadata bytes + out-of-band buffers + refs."""

    __slots__ = ("meta", "buffers", "contained_refs")

    def __init__(self, meta: bytes, buffers: List, contained_refs: List):
        self.meta = meta
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return (
            _HEADER.size
            + len(self.meta)
            + sum(_LEN.size + len(memoryview(b).cast("B")) for b in self.buffers)
        )

    def to_bytes(self) -> bytes:
        out = bytearray()
        self.write_into(out)
        return bytes(out)

    def write_into(self, buf) -> None:
        buf += _HEADER.pack(len(self.buffers), len(self.meta))
        buf += self.meta
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            buf += _LEN.pack(len(mv))
            buf += mv

    def write_into_view(self, out: memoryview) -> int:
        """Write directly into a writable buffer (the shm segment) —
        single copy for large arrays instead of bytearray-then-shm.

        Bulk buffers copy through numpy: CPython's memoryview slice
        assignment runs ~7× slower than a vectorized memcpy for
        multi-MB payloads (measured 2 vs 14 GB/s on the bench box)."""
        off = 0
        header = _HEADER.pack(len(self.buffers), len(self.meta))
        out[off : off + len(header)] = header
        off += len(header)
        out[off : off + len(self.meta)] = self.meta
        off += len(self.meta)
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            ln = _LEN.pack(len(mv))
            out[off : off + len(ln)] = ln
            off += len(ln)
            n = len(mv)
            if n >= (1 << 20):
                np.frombuffer(out, dtype=np.uint8, count=n, offset=off)[:] = (
                    np.frombuffer(mv, dtype=np.uint8)
                )
            else:
                out[off : off + n] = mv
            off += n
        return off


def _find_custom(obj: Any) -> Optional[Tuple[Type, Tuple[Callable, Callable]]]:
    for cls, pair in _custom_serializers.items():
        if isinstance(obj, cls):
            return cls, pair
    return None


# --- by-value pickling for driver-script modules -------------------------
#
# cloudpickle pickles module-level functions BY REFERENCE when their module
# is importable in the pickling process — but a driver script / test module
# sitting outside the worker's import path (reference: shipped via
# runtime_env working_dir) can't be imported there. Modules whose file is
# not reachable from the import roots workers inherit (site-packages, the
# ray_tpu package root, PYTHONPATH, cwd) are registered for by-value
# pickling, so their functions travel like ``__main__`` functions do.

_by_value_checked: set = set()
_worker_roots_cache: Optional[List[str]] = None


def _worker_import_roots() -> List[str]:
    """The import roots a worker subprocess will actually have: a pristine
    interpreter's sys.path (captured once via a subprocess, so .pth-mapped
    editable installs are included) + the ray_tpu package root + PYTHONPATH
    + cwd. Driver-only insertions (pytest rootdir, sys.path.insert in the
    driver script) are deliberately absent."""
    global _worker_roots_cache
    if _worker_roots_cache is not None:
        return _worker_roots_cache
    import os
    import subprocess
    import sys

    roots = set()
    try:
        out = subprocess.run(
            [sys.executable, "-I", "-c", "import sys, json; print(json.dumps(sys.path))"],
            capture_output=True, timeout=20,
        )
        import json

        roots.update(p for p in json.loads(out.stdout) if p)
    except Exception:
        import sysconfig

        for key in ("purelib", "platlib", "stdlib", "platstdlib"):
            try:
                roots.add(sysconfig.get_paths()[key])
            except KeyError:
                pass
    import ray_tpu

    roots.add(os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__))))
    for p in os.environ.get("PYTHONPATH", "").split(os.pathsep):
        if p:
            roots.add(os.path.abspath(p))
    roots.add(os.getcwd())
    _worker_roots_cache = [os.path.abspath(r) for r in roots]
    return _worker_roots_cache


def ensure_importable_or_by_value(obj: Any) -> None:
    """If ``obj``'s defining module can't be imported on workers, register
    it with cloudpickle for by-value pickling (idempotent, cheap)."""
    import os
    import sys

    mod_name = getattr(obj, "__module__", None)
    if not mod_name or mod_name == "__main__" or mod_name in _by_value_checked:
        return
    _by_value_checked.add(mod_name)
    mod = sys.modules.get(mod_name)
    if mod is None or getattr(mod, "__file__", None) is None:
        return
    # Importable on a worker iff ``import <mod_name>`` resolves from one of
    # the worker's import roots — i.e. the name-derived path exists there.
    rel = mod_name.replace(".", os.sep)
    for root in _worker_import_roots():
        if os.path.exists(os.path.join(root, rel + ".py")) or os.path.exists(
            os.path.join(root, rel, "__init__.py")
        ):
            return  # keep by-reference pickling
    try:
        cloudpickle.register_pickle_by_value(mod)
    except Exception:
        pass


# Exact-type primitive fast path for serialize(): these values cannot
# contain ObjectRefs and never need cloudpickle, so the hot result/arg
# path (noop returns, small scalars) skips CloudPickler construction.
_PRIMITIVE_TYPES = frozenset({type(None), bool, int, float, bytes, str})


def serialize(value: Any) -> SerializedValue:
    if type(value) in _PRIMITIVE_TYPES and not _custom_serializers:
        return SerializedValue(pickle.dumps(value, protocol=5), [], [])

    from ray_tpu.core.refs import ObjectRef  # cycle: refs uses serialization

    buffers: List = []
    contained: List = []

    def reducer(obj):
        if isinstance(obj, ObjectRef):
            contained.append(obj)
            return None  # fall through to cloudpickle's default handling
        if _custom_serializers:
            hit = _find_custom(obj)
            if hit is not None:
                cls, (ser, _de) = hit
                return (_reconstruct_custom, (cloudpickle.dumps(cls), ser(obj)))
        return None

    # jax.Array → numpy before pickling (duck-typed to avoid importing jax).
    mod = type(value).__module__ or ""
    if mod.startswith("jaxlib") or mod.startswith("jax"):
        if hasattr(value, "__array__"):
            value = np.asarray(value)

    class _Pickler(cloudpickle.CloudPickler):
        def reducer_override(self, obj):
            rv = reducer(obj)
            if rv is not None:
                return rv
            return super().reducer_override(obj)

    import io

    f = io.BytesIO()
    p = _Pickler(f, protocol=5, buffer_callback=buffers.append)
    p.dump(value)
    return SerializedValue(f.getvalue(), buffers, contained)


def deserialize(meta: bytes, buffers: List) -> Any:
    return pickle.loads(meta, buffers=buffers)


def deserialize_bytes(data) -> Any:
    mv = memoryview(data)
    nbuf, meta_len = _HEADER.unpack_from(mv, 0)
    off = _HEADER.size
    meta = bytes(mv[off : off + meta_len])
    off += meta_len
    buffers = []
    for _ in range(nbuf):
        (blen,) = _LEN.unpack_from(mv, off)
        off += _LEN.size
        buffers.append(pickle.PickleBuffer(mv[off : off + blen]))
        off += blen
    return deserialize(meta, buffers)
