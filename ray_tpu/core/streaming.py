"""Streaming generators: ``num_returns="streaming"``.

Reference: ``core_worker/task_manager.h:102`` (``ObjectRefStream``) and
the Cython generator execution path (``_raylet.pyx:1345``) — a generator
task's yields become ObjectRefs the caller consumes WHILE the task still
runs. The executing worker pushes each item back over the submission
connection (ordered by TCP); the owner records them in an
``ObjectRefStream`` and hands them out through an ``ObjectRefGenerator``.

Retries are disabled for streaming tasks at THIS layer (re-executing a
partially-consumed stream has replay semantics the reference spent a
protocol on; a died worker surfaces as the stream erroring). The serve
router implements replay ABOVE this layer for deployments that declare
``resumable_streams``: items carry a per-request monotonic sequence
number, an interrupted stream is re-dispatched to a survivor with
``resume_from`` set, and :class:`SeqGate` suppresses replayed duplicates
so the client-visible sequence has no gaps and no repeats
(``serve/router.py``).

Transport: items push back over the submission connection. Inline item
bytes at or above ``rpc_raw_stream_min_bytes`` ride RAW frames
(``core/rpc.py`` kind 5) — the bulk payload travels out-of-band and the
owner's push handler receives the reassembled envelope, skipping the
pickle+msgpack copies of the item bytes on both ends; larger items go
to shm and only their location travels, so their bytes ride the
zero-copy RAW chunk-transfer path when a consumer on another node
fetches them.

Producer-side backpressure (the reference's consumer-position protocol):
the generator pauses once ``produced - consumed`` reaches
``streaming_generator_backpressure_items``; the owner's throttled
consumed reports (``w_stream_consumed``) resume it — so a fast producer
against a slow consumer keeps the owner-side buffer bounded by the
threshold, not the stream length. Consumed entries are trimmed, and
abandoning the generator cancels a still-running producer."""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_tpu.core.exceptions import GetTimeoutError
from ray_tpu.core.ids import ObjectID

#: push channel on worker→owner connections carrying stream items
STREAM_PUSH_CHANNEL = 10

_END = object()


class TokenChunk(list):
    """Marker for a COALESCED burst of stream items (serve token
    streaming): a producer that has several items ready at once — e.g. a
    speculative-decoding engine accepting k+1 tokens in one verify step
    — yields them as one ``TokenChunk`` so the burst rides ONE
    ObjectRef/get round trip instead of one per token. The serve router
    flattens chunks before clients see them, so the consumer-visible
    stream is unchanged; the subclass (not a bare list) is what lets the
    router distinguish a coalesced burst from a deployment whose stream
    legitimately yields list VALUES."""

    __slots__ = ()


def streaming_error_result(err) -> tuple:
    """The wire shape for a stream-level failure: streaming specs have no
    fixed return ids, so the empty-oid sentinel routes the error to the
    stream itself (matched in ``CoreWorker._process_reply``). Single
    source — executor and batch paths must agree on this shape."""
    import pickle

    return (b"", "error", pickle.dumps(err))


class SeqGate:
    """Consumer-side duplicate/gap gate for seq-numbered resumable
    streams (serve router exactly-once token delivery).

    Every item of a resumable stream is a ``(seq, value)`` pair with a
    per-request monotonic seq. The gate admits exactly the item whose
    seq it expects next; anything below is a replayed duplicate (a
    failed-over producer re-emitting the boundary item the consumer
    already delivered) and is suppressed; anything above is a protocol
    violation — a resumed producer must start exactly at ``next_seq``,
    so a gap can only mean lost delivery, which must fail loudly rather
    than silently skip items."""

    __slots__ = ("next_seq",)

    def __init__(self, start: int = 0):
        self.next_seq = int(start)

    def admit(self, seq: int) -> bool:
        """True → deliver (and advance); False → suppress a duplicate.
        Raises RuntimeError on a gap."""
        seq = int(seq)
        if seq == self.next_seq:
            self.next_seq += 1
            return True
        if seq < self.next_seq:
            return False
        raise RuntimeError(
            f"resumable stream gap: expected seq {self.next_seq}, got {seq}"
        )


class ObjectRefStream:
    """Owner-side record of one streaming task's yielded refs."""

    def __init__(self, task_id: bytes):
        self.task_id = task_id
        self._items: Dict[int, ObjectID] = {}  # 1-based index -> object id
        self._total: Optional[int] = None
        self._error: Optional[Exception] = None
        self._cond = threading.Condition()

    def append(self, index: int, object_id: ObjectID) -> None:
        with self._cond:
            self._items[index] = object_id
            self._cond.notify_all()

    def complete(self, total: int) -> None:
        with self._cond:
            self._total = total
            self._cond.notify_all()

    def fail(self, error: Exception) -> None:
        with self._cond:
            self._error = error
            self._cond.notify_all()

    def next_blocking(self, index: int, timeout: Optional[float]):
        """Block until item ``index`` exists; returns its ObjectID,
        ``_END`` past the last item, or raises the stream error. The
        consumed entry is dropped so the map holds only the unconsumed
        backlog, not the whole stream history."""
        with self._cond:
            while True:
                if index in self._items:
                    return self._items.pop(index)
                if self._error is not None:
                    raise self._error
                if self._total is not None and index > self._total:
                    return _END
                if not self._cond.wait(timeout):
                    raise GetTimeoutError(
                        f"stream item {index} not produced in time"
                    )


class ObjectRefGenerator:
    """User-facing iterator over a streaming task's item refs
    (reference ``ObjectRefGenerator``)."""

    def __init__(self, backend, task_id: bytes, owner_address):
        self._backend = backend
        self._task_id = task_id
        self._owner = owner_address
        self._pos = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self):
        return self.next_with_timeout(None)

    def next_with_timeout(self, timeout):
        """Like ``__next__`` but bounded: raises TimeoutError if no item
        (or end-of-stream) arrives in ``timeout`` seconds — what lets a
        serving router cap time-to-first-token instead of parking
        forever on a stuck producer."""
        from ray_tpu.core.refs import ObjectRef

        self._pos += 1
        try:
            oid = self._backend.stream_next(self._task_id, self._pos, timeout=timeout)
        except Exception:
            self._pos -= 1  # not consumed — a retry re-requests this index
            raise
        if oid is _END:
            raise StopIteration
        ref = ObjectRef(oid, self._owner)
        self._backend.release_hold([oid])
        return ref

    def abandon(self) -> None:
        """Explicitly release this stream: drop the owner-side holds on
        items never handed out and cancel a still-running producer (the
        owner forwards a cooperative ``cancel_task`` to the executing
        worker, which closes the producing generator — an engine request
        behind it gets ``cancel()``ed and frees its KV blocks). Idempotent
        and safe after exhaustion (a finished stream has nothing running
        to cancel). Called by consumers that stop reading mid-stream —
        the serve router's stream wrappers call it on ``close()`` so an
        HTTP client disconnect propagates all the way down — and by
        ``__del__`` as the GC backstop."""
        try:
            abandon = getattr(self._backend, "abandon_stream", None)
            if abandon is not None:
                abandon(self._task_id, self._pos)
        except Exception:
            pass

    def __del__(self):
        # Abandoned before exhaustion: release the owner-side holds on
        # items never handed out, or they pin memory forever.
        self.abandon()

    def __repr__(self) -> str:
        return f"ObjectRefGenerator({self._task_id.hex()[:16]}, pos={self._pos})"
