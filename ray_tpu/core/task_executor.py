"""Worker-side task execution engine.

Reference: ``core_worker/transport/task_receiver.h:91`` + the scheduling
queues — normal tasks FIFO on a single lane; actor tasks ordered per
caller by sequence number (``SequentialActorSubmitQueue``), thread-pool
lanes for ``max_concurrency`` / concurrency groups
(``ConcurrencyGroupManager``), an asyncio lane for async (coroutine)
actor methods (fibers, ``fiber.h``), and result packaging: small returns
inline in the reply, large returns into the node shm store
(reference: task output plasma promotion).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import pickle
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import execution, serialization
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.exceptions import TaskCancelledError, TaskError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.task_spec import TaskKind, TaskSpec

logger = logging.getLogger(__name__)


class TaskExecutor:
    def __init__(self):
        self.core = None  # CoreWorker
        self.api_worker = None  # api.Worker
        self._lanes: Dict[str, ThreadPoolExecutor] = {}
        self._default_lane = ThreadPoolExecutor(max_workers=1, thread_name_prefix="exec")
        self._actor_instance: Any = None
        self._actor_spec: Optional[TaskSpec] = None
        self._max_concurrency = 1
        # per-caller ordering state: caller worker_id -> {next, cond}
        self._seq: Dict[bytes, Dict[str, Any]] = {}
        self._async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._async_sem: Optional[asyncio.Semaphore] = None
        # cancellation (``CoreWorker::CancelTask``): ids cancelled before
        # execution start + thread idents of tasks currently executing
        self._cancelled: set = set()
        self._running_threads: Dict[bytes, int] = {}
        # threads currently running a BATCH (handle_push_batch_fast):
        # async-exc delivery is unsafe there — a late exception would
        # land in a batchmate, not an idle retired thread
        self._batch_idents: set = set()
        self._cancel_lock = threading.Lock()
        self._env_gen = 0  # runtime-env application generation
        # streaming backpressure: owner-reported consumer positions
        self._stream_consumed: Dict[bytes, int] = {}
        self._stream_events: Dict[bytes, threading.Event] = {}
        self._stream_lock = threading.Lock()

    def bind(self, core, api_worker) -> None:
        self.core = core
        self.api_worker = api_worker

    # ------------------------------------------------------------------
    def _lane_for(self, spec: TaskSpec) -> ThreadPoolExecutor:
        if spec.concurrency_group and spec.concurrency_group in self._lanes:
            return self._lanes[spec.concurrency_group]
        return self._default_lane

    def _get_dep(self, ref) -> Any:
        values = self.core.get_objects([ref], timeout=None)
        value = values[0]
        if isinstance(value, Exception):
            raise value if isinstance(value, TaskError) else TaskError("dependency", value)
        return value

    # ------------------------------------------------------------------
    async def handle_actor_creation(self, spec: TaskSpec) -> Dict[str, Any]:
        # The daemon can dispatch creation while our registration reply is
        # still in flight; wait for the handshake to finish.
        for _ in range(500):
            if self.core is not None and self.core.address is not None:
                break
            await asyncio.sleep(0.01)
        self._actor_spec = spec
        self._max_concurrency = max(1, spec.max_concurrency)
        if self._max_concurrency > 1:
            self._default_lane = ThreadPoolExecutor(
                max_workers=self._max_concurrency, thread_name_prefix="actor"
            )
        for group, limit in (spec.concurrency_groups or {}).items():
            self._lanes[group] = ThreadPoolExecutor(max_workers=max(1, limit), thread_name_prefix=group)
        loop = asyncio.get_event_loop()

        def _create():
            self.api_worker.job_id = spec.job_id
            self.api_worker.current_actor_id = spec.actor_id
            self.api_worker.assigned_resources = dict(spec.resources or {})
            self.api_worker.set_task_context(spec.task_id, spec.job_id)
            # dedicated worker: runtime-env vars apply for its lifetime
            self._apply_runtime_env(spec)
            cls = self.api_worker.fn_table.load(spec.function_id)
            args, kwargs = execution.resolve_args(spec, self._get_dep)
            self._actor_instance = cls(*args, **kwargs)

        try:
            await loop.run_in_executor(self._default_lane, _create)
        except Exception as e:  # noqa: BLE001
            err = TaskError(spec.name, e)
            await self.core.controller.call(
                "actor_creation_failed",
                {
                    "actor_id": spec.actor_id,
                    "reason": f"creation failed: {e!r}",
                    "error": pickle.dumps(err),
                },
            )
            # exit so the daemon reaps this dedicated worker
            self.core.io.loop.call_later(0.1, _exit_now)
            return {"ok": False}
        await self.core.controller.call(
            "actor_ready",
            {"actor_id": spec.actor_id, "address": self.core.address},
        )
        return {"ok": True}

    # ------------------------------------------------------------------
    def _make_emit(self, spec: TaskSpec, conn):
        """Stream items push back over the submission connection, ordered
        by TCP (reference: generator returns stream through the reply
        channel, _raylet.pyx:1345). Shared by the normal-task and
        actor-task paths."""
        if spec.num_returns != "streaming" or conn is None:
            return None
        from ray_tpu.core.config import GLOBAL_CONFIG
        from ray_tpu.core.streaming import STREAM_PUSH_CHANNEL

        loop_ = asyncio.get_event_loop()

        def emit(payload):  # runs on the lane thread
            # inline items past the threshold ride a RAW push: the item
            # bytes travel out-of-band (zero pickle/msgpack of the bulk
            # on either end); the receiver reassembles envelope["data"]
            # and the owner-side handler is shape-identical
            raw_min = GLOBAL_CONFIG.rpc_raw_stream_min_bytes
            data = payload.get("data")
            if (
                raw_min >= 0
                and data is not None
                and len(data) >= raw_min
            ):
                envelope = {k: v for k, v in payload.items() if k != "data"}
                coro = conn.push_raw(STREAM_PUSH_CHANNEL, envelope, data)
            else:
                coro = conn.push(STREAM_PUSH_CHANNEL, payload)
            asyncio.run_coroutine_threadsafe(coro, loop_).result(timeout=60)

        return emit

    def handle_push_batch_fast(self, specs: List[TaskSpec], conn=None):
        """Single-dispatch batch execution, or None when the batch's
        shape needs the general per-spec path. A lane handoff costs two
        condvar hops (slow syscalls under load) — for a micro-task batch
        those dominate, so the whole batch rides ONE run_in_executor.

        Covered shapes: all plain non-streaming normal tasks, or all
        non-streaming sync methods of THIS ordered (max_concurrency==1,
        no concurrency groups) actor from one caller — the batch is
        executed serially in order, exactly like the per-spec path."""
        if len(specs) < 2:
            return None
        loop = asyncio.get_event_loop()
        if all(
            s.kind == TaskKind.NORMAL and s.num_returns != "streaming"
            for s in specs
        ):
            return loop.run_in_executor(
                self._default_lane, self._execute_batch, specs
            )
        if (
            self._max_concurrency == 1
            and not self._lanes
            and all(
                s.kind == TaskKind.ACTOR_TASK
                and s.num_returns != "streaming"
                and not s.concurrency_group
                and s.method_name
                and not s.method_name.startswith("__ray_")
                and not inspect.iscoroutinefunction(
                    getattr(self._actor_instance, s.method_name, None)
                )
                and s.owner is not None
                and s.owner.worker_id == specs[0].owner.worker_id
                for s in specs
            )
        ):
            return self._ordered_actor_batch(specs)
        return None

    async def _ordered_actor_batch(self, specs: List[TaskSpec]) -> List[Dict[str, Any]]:
        caller = specs[0].owner.worker_id
        await self._wait_turn(caller, specs[0].seq_no)
        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(self._default_lane, self._execute_batch, specs)
        # the whole batch occupies the single-thread lane serially, so
        # advancing all seqs now preserves the per-caller order contract
        # (exactly like per-spec dispatch into the same lane)
        for _ in specs:
            self._advance(caller)
        return await fut

    def _execute_batch(self, specs: List[TaskSpec]) -> List[Dict[str, Any]]:
        """Lane thread: run a batch serially with per-spec isolation.

        The thread registers itself as batch-running so cancel_task
        switches to cooperative-only delivery (the async-exc mechanism
        assumes a retired lane's thread goes idle after its one task —
        here it would poison a batchmate instead)."""
        ident = threading.get_ident()
        with self._cancel_lock:
            self._batch_idents.add(ident)
        try:
            replies = []
            for spec in specs:
                try:
                    replies.append({"results": self._execute(spec)})
                except Exception as e:  # noqa: BLE001 — isolate batchmates
                    logger.exception("task %s failed in batch", spec.name)
                    err = TaskError(spec.name, e)
                    replies.append(
                        {
                            "results": [
                                (oid.binary(), "error", pickle.dumps(err))
                                for oid in spec.return_ids
                            ]
                        }
                    )
            return replies
        finally:
            with self._cancel_lock:
                self._batch_idents.discard(ident)

    async def handle_push_task(self, spec: TaskSpec, conn=None) -> Dict[str, Any]:
        if spec.kind == TaskKind.ACTOR_TASK:
            return await self._handle_actor_task(spec, conn)
        logger.debug("executing %s %s", spec.name, spec.task_id.hex()[:8])
        emit = self._make_emit(spec, conn)

        # Normal tasks run on the pooled lane (thread spawn per task costs
        # real throughput). Cancellation safety: cancel_task delivers
        # TaskCancelledError via PyThreadState_SetAsyncExc and immediately
        # RETIRES the lane (fresh pool) — a stray exception firing after
        # the task finished lands in the abandoned pool's thread, never in
        # a later task. The lane holds at most the one running task (the
        # lease protocol serializes pushes), so nothing queued is lost.
        loop = asyncio.get_event_loop()
        results = await loop.run_in_executor(
            self._default_lane, self._execute, spec, emit
        )
        logger.debug("finished %s %s", spec.name, spec.task_id.hex()[:8])
        return {"results": results}

    async def _handle_actor_task(self, spec: TaskSpec, conn=None) -> Dict[str, Any]:
        # built-in methods
        if spec.method_name == "__ray_ready__":
            return {"results": self._package(spec, [(spec.return_ids[0], True)])}
        if spec.method_name == "__ray_terminate__":
            reply = {"results": self._package(spec, [(spec.return_ids[0], None)])}
            self.core.io.loop.call_later(0.05, _exit_now)
            return reply
        if spec.method_name == "__ray_dag_loop__":
            # compiled-graph loop (``dag/compiled.py``): occupies the
            # default lane until the driver tears the DAG down — the
            # reply to this call IS the loop's exit signal
            from ray_tpu.dag.compiled import run_dag_loop

            loop = asyncio.get_event_loop()

            def _run_loop():
                args, _kwargs = execution.resolve_args(spec, self._get_dep)
                run_dag_loop(self._actor_instance, args[0])

            try:
                await loop.run_in_executor(self._default_lane, _run_loop)
                pairs = [(spec.return_ids[0], None)]
            except Exception as e:  # noqa: BLE001
                pairs = [(spec.return_ids[0], TaskError(spec.name, e))]
            return {"results": await loop.run_in_executor(None, self._package, spec, pairs)}
        method = getattr(self._actor_instance, spec.method_name, None)
        if method is None:
            err = TaskError(spec.name, AttributeError(f"no method {spec.method_name!r}"))
            return {"results": [(oid.binary(), "error", pickle.dumps(err)) for oid in spec.return_ids]}
        if spec.num_returns == "streaming" and inspect.iscoroutinefunction(method):
            err = TaskError(
                spec.name,
                TypeError(
                    "streaming actor method must be a (sync or async) "
                    "generator, not a coroutine returning a value"
                ),
            )
            from ray_tpu.core.streaming import streaming_error_result

            return {"results": [streaming_error_result(err)]}
        if inspect.iscoroutinefunction(method):
            try:
                self._apply_runtime_env(spec)  # dedicated worker: permanent
            except Exception as e:  # noqa: BLE001 — malformed runtime_env
                err = TaskError(spec.name, ValueError(f"bad runtime_env: {e!r}"))
                return {
                    "results": [
                        (oid.binary(), "error", pickle.dumps(err))
                        for oid in spec.return_ids
                    ]
                }
            return await self._run_async_method(spec, method)
        emit = self._make_emit(spec, conn)
        caller = spec.owner.worker_id if spec.owner else b""
        if self._max_concurrency == 1 and not spec.concurrency_group:
            await self._wait_turn(caller, spec.seq_no)
            # submission order into the single-thread lane = execution order
            loop = asyncio.get_event_loop()
            fut = loop.run_in_executor(self._lane_for(spec), self._execute, spec, emit)
            self._advance(caller)
            results = await fut
        else:
            loop = asyncio.get_event_loop()
            results = await loop.run_in_executor(self._lane_for(spec), self._execute, spec, emit)
        return {"results": results}

    async def _wait_turn(self, caller: bytes, seq: int) -> None:
        state = self._seq.get(caller)
        if state is None:
            # Baseline at the first sequence number seen from this caller:
            # after an actor restart the caller's counter keeps counting,
            # so starting from 1 would deadlock (reference handles this via
            # caller_starts_at in the actor submit queue).
            state = self._seq[caller] = {"next": seq, "cond": asyncio.Condition()}
        async with state["cond"]:
            await state["cond"].wait_for(lambda: state["next"] >= seq)

    def _advance(self, caller: bytes) -> None:
        state = self._seq.get(caller)
        if state is None:
            return

        async def _notify():
            async with state["cond"]:
                state["next"] += 1
                state["cond"].notify_all()

        asyncio.ensure_future(_notify())

    async def _run_async_method(self, spec: TaskSpec, method) -> Dict[str, Any]:
        """Async actor methods run on a dedicated loop with a
        max_concurrency semaphore (reference: fibers for async actors)."""
        if self._async_loop is None:
            self._async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._async_loop.run_forever, daemon=True, name="actor-async")
            t.start()
            # user coroutines run here: a method that blocks this loop
            # stalls every other async call on the actor — watchdog it
            from ray_tpu.observability.event_stats import install_loop_monitor

            install_loop_monitor(self._async_loop, "actor-async")

        loop0 = asyncio.get_event_loop()
        # Arg resolution can block on remote objects — keep it off the io
        # loop. Pure-inline args (the common small-call shape) can't
        # block: resolve them right here and skip the thread hop.
        if any(t == "ref" for t, _ in spec.args) or any(
            t == "ref" for t, _k, _v in spec.kwargs
        ):
            args, kwargs = await loop0.run_in_executor(
                None, execution.resolve_args, spec, self._get_dep
            )
        else:
            args, kwargs = execution.resolve_args(spec, self._get_dep)

        async def _run():
            # Per-coroutine task context (ContextVar) so puts made inside the
            # async method derive ObjectIDs from THIS task's id, not the
            # deterministic driver id — two async actors in one job would
            # otherwise mint colliding ObjectIDs (shm segments are named by
            # ObjectID, so a collision silently overwrites data).
            from ray_tpu.core.deadline import deadline_scope
            from ray_tpu.observability import tracing as _tracing

            self.api_worker.job_id = spec.job_id
            self.api_worker.set_task_context(spec.task_id, spec.job_id)
            if self._async_sem is None:
                self._async_sem = asyncio.Semaphore(max(1, self._max_concurrency))
            async with self._async_sem:
                with deadline_scope(spec.deadline_remaining_s):
                    if spec.trace_ctx is not None:
                        # async actor methods (e.g. serve replicas) get
                        # the same causal re-entry as lane-thread tasks
                        with _tracing.scope(spec.trace_ctx), _tracing.span(
                            f"task::{spec.name}", "task",
                            task_id=spec.task_id.hex()[:16],
                        ):
                            return await method(*args, **kwargs)
                    return await method(*args, **kwargs)

        cfut = asyncio.run_coroutine_threadsafe(_run(), self._async_loop)
        loop = asyncio.get_event_loop()
        try:
            # await the cross-loop future directly — the old
            # run_in_executor(None, cfut.result) parked an executor
            # thread per in-flight call (two futex hops each)
            result = await asyncio.wrap_future(cfut)
            pairs = execution.unpack_returns(spec, result)
        except Exception as e:  # noqa: BLE001
            err = TaskError(spec.name, e)
            pairs = [(oid, err) for oid in spec.return_ids]
        # _package can RPC the daemon (large results) — keep it off the io loop
        return {"results": await loop.run_in_executor(None, self._package, spec, pairs)}

    # ------------------------------------------------------------------
    def _execute(self, spec: TaskSpec, emit=None) -> List[Tuple[bytes, str, Any]]:
        """Runs on a lane thread. Returns packaged results."""
        from ray_tpu.core.deadline import deadline_scope
        from ray_tpu.observability import timeline as _timeline
        from ray_tpu.observability import tracing as _tracing
        from ray_tpu.observability.rpc_metrics import TASK_STAGE_SECONDS

        _start_us = _timeline._now_us()
        try:
            # re-enter the submitter's remaining budget: nested get()/wait()
            # inside this task inherit the caller's deadline (deadline
            # propagation, hang defense). Traced specs additionally
            # re-enter the submitter's TRACE: this task's span parents to
            # the caller's, and everything nested under it (submits of
            # child tasks, actor calls, RPCs) parents to this task's span
            # — the cross-process causal chain.
            if spec.trace_ctx is not None:
                with _tracing.scope(spec.trace_ctx), _tracing.span(
                    f"task::{spec.name}", "task",
                    task_id=spec.task_id.hex()[:16],
                ), deadline_scope(spec.deadline_remaining_s):
                    return self._execute_inner(spec, emit)
            with deadline_scope(spec.deadline_remaining_s):
                return self._execute_inner(spec, emit)
        finally:
            end_us = _timeline._now_us()
            TASK_STAGE_SECONDS.observe(
                (end_us - _start_us) / 1e6, labels={"stage": "execute"}
            )
            if spec.trace_ctx is None:
                # traced specs already recorded their span above — one
                # event per execution either way
                _timeline.record_event(
                    f"task::{spec.name}",
                    "task",
                    _start_us,
                    end_us,
                    args={"task_id": spec.task_id.hex()[:16]},
                )

    def _apply_runtime_env(self, spec: TaskSpec):
        """Minimal runtime-env support (reference
        ``_private/runtime_env/``): ``env_vars`` apply for the task's
        duration on pooled workers (restored afterwards — the pool is
        shared) and permanently on dedicated actor workers. Returns a
        restore callable or None.

        The restore is generation-guarded: a cancelled task's thread can
        overlap the next task briefly (retired-lane window), and a stale
        restore must not clobber the newer task's environment. Nested
        overlap can still leave the older values applied — the reference
        avoids this class of problem entirely by dedicating workers per
        runtime env, which is the upgrade path here too."""
        env = spec.runtime_env or {}
        if not env:
            return None
        from ray_tpu.runtime_env import apply_runtime_env

        permanent = spec.kind != TaskKind.NORMAL or spec.actor_id is not None
        restores = apply_runtime_env(
            env, self.api_worker.backend.kv_get, permanent=permanent
        )
        if not restores:
            return None
        self._env_gen += 1
        my_gen = self._env_gen

        def restore():
            if self._env_gen != my_gen:
                return  # a newer task re-applied an env: don't clobber
            for r in restores:
                r()

        return restore

    def update_stream_consumed(self, task_id: bytes, consumed: int) -> None:
        """Owner's consumer-position report: wakes a producer paused on
        backpressure (reference stream consumer-position protocol)."""
        with self._stream_lock:
            ev = self._stream_events.get(task_id)
            if ev is None:
                return  # stream finished: a late report must not re-insert
            if consumed > self._stream_consumed.get(task_id, 0):
                self._stream_consumed[task_id] = consumed
        ev.set()

    def cancel_task(self, task_id: bytes, force: bool) -> bool:
        """Cooperative (or forced) cancellation (``CoreWorker::CancelTask``).

        Queued tasks are marked and rejected at the dep-resolution /
        execution boundary; a RUNNING task gets TaskCancelledError raised
        asynchronously in its lane thread; ``force`` exits the worker
        process (the daemon reaps it, the submitter sees the connection
        drop)."""
        if force:
            self.core.io.loop.call_later(0.05, _exit_now)
            return True
        with self._cancel_lock:
            self._cancelled.add(task_id)
            ident = self._running_threads.get(task_id)
            if ident is not None and ident in self._batch_idents:
                # Batch-running thread: async-exc could land in a
                # batchmate (the thread is NOT idle after the target
                # finishes). Cooperative-only — queued batchmates see
                # the cancel mark at their execution boundary; the
                # running task completes (force=True still kills the
                # process).
                ident = None
        if ident is not None:
            import ctypes

            # Retire the lane BEFORE delivering: if the exception fires
            # after the task completes, it lands in the abandoned pool's
            # (now-idle) thread instead of poisoning the next task.
            # shutdown(wait=False) wakes an idle old thread so it exits
            # rather than parking forever with the exc pending.
            old = self._default_lane
            self._default_lane = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="exec"
            )
            old.shutdown(wait=False)
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError)
            )
        return True

    def _execute_inner(self, spec: TaskSpec, emit=None) -> List[Tuple[bytes, str, Any]]:
        self.api_worker.job_id = spec.job_id
        self.api_worker.set_task_context(spec.task_id, spec.job_id)
        if spec.kind != TaskKind.ACTOR_TASK:  # actors keep creation-time resources
            self.api_worker.assigned_resources = dict(spec.resources or {})
        tid = spec.task_id.binary()

        def error_results(err) -> List[Tuple[bytes, str, Any]]:
            # streaming specs have no fixed return ids: the error must
            # still reach the owner (as a stream failure) or the consumer
            # blocks forever on a stream that never finalizes
            if spec.num_returns == "streaming":
                from ray_tpu.core.streaming import streaming_error_result

                return [streaming_error_result(err)]
            return [
                (oid.binary(), "error", pickle.dumps(err))
                for oid in spec.return_ids
            ]

        with self._cancel_lock:
            if tid in self._cancelled:
                self._cancelled.discard(tid)  # consumed — don't grow forever
                return error_results(TaskCancelledError(spec.task_id.hex()[:16]))
            if len(self._cancelled) > 4096:
                self._cancelled.clear()  # stale marks on a long-lived worker
            if spec.kind != TaskKind.ACTOR_TASK:
                # only normal tasks are async-exc cancellable (actor tasks
                # share pooled lane threads where a stray exception would
                # poison peers)
                self._running_threads[tid] = threading.get_ident()
        if spec.kind != TaskKind.ACTOR_TASK:
            self.core.emit_task_event(spec, "RUNNING")
        env_restore = None
        try:
            try:
                env_restore = self._apply_runtime_env(spec)
            except Exception as e:  # noqa: BLE001 — malformed runtime_env
                return error_results(
                    TaskError(spec.name, ValueError(f"bad runtime_env: {e!r}"))
                )
            try:
                if spec.kind == TaskKind.ACTOR_TASK:
                    fn = getattr(self._actor_instance, spec.method_name)
                else:
                    fn = self.api_worker.fn_table.load(spec.function_id)
                args, kwargs = execution.resolve_args(spec, self._get_dep)
            except TaskCancelledError:
                return error_results(TaskCancelledError(spec.task_id.hex()[:16]))
            except Exception as e:  # noqa: BLE001
                err = e if isinstance(e, TaskError) else TaskError(spec.name, e)
                return error_results(err)
            if spec.num_returns == "streaming":
                return self._execute_streaming(spec, fn, args, kwargs, emit)
            pairs = execution.run_function(spec, fn, args, kwargs)
        finally:
            with self._cancel_lock:
                self._running_threads.pop(tid, None)
            if env_restore is not None:
                env_restore()
        # (env restore is generation-guarded: see _apply_runtime_env)
        # An async-raised TaskCancelledError lands as the TaskError cause:
        # surface it as the cancellation itself, not an app failure.
        out: List[Tuple[ObjectID, Any]] = []
        for oid, value in pairs:
            if isinstance(value, TaskError) and isinstance(
                getattr(value, "cause", None), TaskCancelledError
            ):
                value = TaskCancelledError(spec.task_id.hex()[:16])
            out.append((oid, value))
        return self._package(spec, out)

    def _execute_streaming(
        self, spec: TaskSpec, fn, args, kwargs, emit
    ) -> List[Tuple[bytes, str, Any]]:
        """Generator task: each yielded value becomes an ObjectRef pushed
        to the owner IMMEDIATELY (consumable before the task finishes);
        the reply carries only the end-of-stream marker."""
        from ray_tpu.core.streaming import streaming_error_result

        if emit is None:
            err = TaskError(
                spec.name,
                RuntimeError("streaming task executed without a stream channel"),
            )
            return [streaming_error_result(err)]
        tid = spec.task_id.binary()
        threshold = GLOBAL_CONFIG.streaming_generator_backpressure_items
        if threshold > 0:
            with self._stream_lock:
                self._stream_events[tid] = threading.Event()
        count = 0
        result = None
        try:
            result = fn(*args, **kwargs)
            if inspect.isasyncgen(result):
                # async generator driven from this lane thread on a
                # private loop (reference: async streaming replicas)
                result = _drain_async_gen(result)
            elif not inspect.isgenerator(result) and not hasattr(result, "__iter__"):
                raise TypeError(
                    f"num_returns='streaming' task {spec.name} must return "
                    f"a generator/iterable, got {type(result).__name__}"
                )
            for value in result:
                count += 1
                # cooperative cancel consulted on EVERY item, not only in
                # the backpressure wait below: an abandoned stream (the
                # consumer's ObjectRefGenerator was dropped/closed — e.g.
                # an HTTP client disconnected mid-SSE) must stop the
                # producer within one item, not after it outruns the
                # consumer by a full backpressure window. The finally
                # close()s the generator, so a producer built on
                # engine.generate() runs its cancel() cleanup and frees
                # its KV blocks promptly.
                with self._cancel_lock:
                    if tid in self._cancelled:
                        self._cancelled.discard(tid)
                        raise TaskCancelledError(spec.task_id.hex()[:16])
                # producer-side backpressure: pause while the consumer
                # lags by more than the threshold; the owner's consumed
                # reports (w_stream_consumed) resume us. Cancellation is
                # still honored while paused.
                if threshold > 0:
                    while (
                        count - self._stream_consumed.get(tid, 0) > threshold
                    ):
                        with self._cancel_lock:
                            if tid in self._cancelled:
                                self._cancelled.discard(tid)
                                raise TaskCancelledError(spec.task_id.hex()[:16])
                        ev = self._stream_events.get(tid)
                        if ev is None:
                            break
                        ev.clear()
                        if count - self._stream_consumed.get(tid, 0) <= threshold:
                            break
                        ev.wait(0.5)
                oid = ObjectID.from_index(spec.task_id, count)
                kind, payload = self._store_value(oid, value, spec.name)
                if kind == "error":
                    return [streaming_error_result(pickle.loads(payload))]
                emit(
                    {
                        "task_id": spec.task_id.binary(),
                        "index": count,
                        "object_id": oid.binary(),
                        "kind": kind,
                        "data" if kind == "inline" else "location": payload,
                    }
                )
        except TaskCancelledError as e:
            # surfaced as the cancellation itself (the owner usually
            # abandoned the stream and isn't reading), not an app failure
            return [streaming_error_result(e)]
        except Exception as e:  # noqa: BLE001
            err = e if isinstance(e, TaskError) else TaskError(spec.name, e)
            return [streaming_error_result(err)]
        finally:
            # Close the producer DETERMINISTICALLY (not on GC): a cancel/
            # error exit leaves the generator suspended at its last yield,
            # and its finally blocks (engine.generate -> engine.cancel,
            # replica ongoing-count decrement) must run before this task
            # slot is reported free.
            close = getattr(result, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — cleanup must not mask
                    pass
            with self._stream_lock:
                self._stream_consumed.pop(tid, None)
                self._stream_events.pop(tid, None)
        return [(b"", "stream_end", count)]

    def _store_value(self, oid: ObjectID, value: Any, name: str = "") -> Tuple[str, Any]:
        """Promote one result value: inline bytes under the threshold,
        else a sealed shm object adopted by the daemon. Shared by the
        reply packager and the streaming item path so the promotion
        protocol can never diverge between them."""
        try:
            ser = serialization.serialize(value)
        except Exception as e:  # noqa: BLE001
            return ("error", pickle.dumps(TaskError(name or "serialize", e)))
        # results use their own inline ceiling (inline returns ride the
        # task-done reply to the owner's in-process cache; puts/args keep
        # max_direct_call_object_size)
        if ser.total_bytes <= GLOBAL_CONFIG.inline_result_threshold_bytes:
            return ("inline", ser.to_bytes())
        size = self.core.shm.create_and_write(oid, ser)
        self.core.io.run(
            self.core.daemon.call(
                "adopt_object", {"object_id": oid.binary(), "size": size}
            )
        )
        self.core.shm.release(oid)
        return ("shm", self.core._self_location())

    def _package(self, spec: TaskSpec, pairs: List[Tuple[ObjectID, Any]]) -> List[Tuple[bytes, str, Any]]:
        out: List[Tuple[bytes, str, Any]] = []
        for oid, value in pairs:
            if isinstance(value, (TaskError, TaskCancelledError)):
                out.append((oid.binary(), "error", pickle.dumps(value)))
                continue
            kind, payload = self._store_value(oid, value, spec.name)
            out.append((oid.binary(), kind, payload))
        return out


def _drain_async_gen(agen):
    """Sync iterator over an async generator, driven on a private event
    loop owned by the calling (lane) thread."""
    loop = asyncio.new_event_loop()
    try:
        while True:
            try:
                yield loop.run_until_complete(agen.__anext__())
            except StopAsyncIteration:
                return
    finally:
        loop.run_until_complete(agen.aclose())
        loop.close()


def _exit_now():
    import os

    os._exit(0)
