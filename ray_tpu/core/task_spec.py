"""Task / actor specifications and call options.

Equivalent of the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h``) plus the normalized ``.options(...)``
surface (``python/ray/remote_function.py:189``, ``python/ray/actor.py``).
Specs are plain picklable dataclasses; function/class bodies travel by
export-id through the control plane's KV (function-manager pattern,
reference ``_private/function_manager.py``), never inside the spec.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID
from ray_tpu.core.refs import Address, ObjectRef
from ray_tpu.core.resources import ResourceSet


class SchedulingStrategy:
    """Base for scheduling strategies (cf. ``util/scheduling_strategies.py``)."""


@dataclass(frozen=True)
class DefaultScheduling(SchedulingStrategy):
    pass


@dataclass(frozen=True)
class SpreadScheduling(SchedulingStrategy):
    pass


@dataclass(frozen=True)
class NodeAffinityScheduling(SchedulingStrategy):
    node_id: bytes
    soft: bool = False


@dataclass(frozen=True)
class PlacementGroupScheduling(SchedulingStrategy):
    pg_id: bytes
    bundle_index: int = -1  # -1 = any bundle
    capture_child_tasks: bool = False


@dataclass(frozen=True)
class NodeLabelScheduling(SchedulingStrategy):
    hard: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
    soft: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()


class TaskKind(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


@dataclass
class TaskOptions:
    """Normalized ``.options(...)``/``@remote(...)`` arguments."""

    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    resources: Dict[str, float] = field(default_factory=dict)
    memory: Optional[float] = None
    num_returns: Any = None  # int | "dynamic" | "streaming"
    max_retries: Optional[int] = None
    retry_exceptions: Any = False  # bool | list of exception types
    name: Optional[str] = None
    scheduling_strategy: SchedulingStrategy = field(default_factory=DefaultScheduling)
    runtime_env: Optional[Dict[str, Any]] = None
    # actor-only
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: Optional[int] = None
    max_pending_calls: int = -1
    lifetime: Optional[str] = None  # None | "detached"
    namespace: Optional[str] = None
    get_if_exists: bool = False
    concurrency_groups: Dict[str, int] = field(default_factory=dict)

    def resource_request(self, default_cpus: float = 1.0) -> ResourceSet:
        req: Dict[str, float] = dict(self.resources)
        cpus = self.num_cpus if self.num_cpus is not None else default_cpus
        if cpus:
            req["CPU"] = req.get("CPU", 0) + cpus
        if self.num_tpus:
            req["TPU"] = req.get("TPU", 0) + self.num_tpus
        if self.memory:
            req["memory"] = req.get("memory", 0) + self.memory
        return ResourceSet(req)

    def merged_with(self, **updates) -> "TaskOptions":
        import copy

        out = copy.copy(self)
        out.resources = dict(self.resources)
        out.concurrency_groups = dict(self.concurrency_groups)
        for k, v in updates.items():
            if v is None and k not in ("num_returns",):
                continue
            if not hasattr(out, k):
                raise TypeError(f"unknown option: {k}")
            setattr(out, k, v)
        return out


@dataclass
class TaskSpec:
    """One invocation: a normal task, actor creation, or actor method call."""

    kind: TaskKind
    task_id: TaskID
    job_id: JobID
    name: str
    function_id: bytes  # key into the exported-function KV
    # Serialized positional/keyword args. Each entry is either
    # ("ref", ObjectRef) or ("val", bytes) — small args inline (reference
    # DependencyResolver inlining, ``normal_task_submitter.h``).
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: List[Tuple[str, str, Any]] = field(default_factory=list)
    num_returns: Any = 1
    return_ids: List[ObjectID] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_strategy: SchedulingStrategy = field(default_factory=DefaultScheduling)
    owner: Optional[Address] = None
    max_retries: int = 0
    retry_exceptions: Any = False
    runtime_env: Optional[Dict[str, Any]] = None
    # Remaining seconds of the submitter's ambient Deadline at submission
    # (core/deadline.py): the executing worker re-enters this budget so
    # nested get()/wait() inside the task inherit the caller's deadline
    # instead of stacking fresh independent timeouts. None = no budget.
    deadline_remaining_s: Optional[float] = None
    # actor creation
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    actor_name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None
    method_opts: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # actor task
    method_name: Optional[str] = None
    seq_no: int = 0
    concurrency_group: Optional[str] = None
    # Distributed-tracing context (observability/tracing.py): the
    # submitter's (trace_id, span_id) pair, stamped per call the same
    # way deadline_remaining_s is — the executing worker re-enters the
    # context so its spans (and nested submits) parent to the caller's.
    # None = untraced (the sampling-off default; zero wire overhead
    # beyond one tuple slot).
    trace_ctx: Optional[Tuple[str, str]] = None
    # Set when this spec was spliced from a cached SpecTemplate: the
    # submit path ships (template_id, per-call fields) instead of the
    # full spec — executors rebuild it from their template cache.
    template_id: Optional[bytes] = None

    def dependencies(self) -> List[ObjectRef]:
        deps = [a for t, a in self.args if t == "ref"]
        deps += [v for t, _k, v in self.kwargs if t == "ref"]
        return deps


@dataclass
class SpecTemplate:
    """Invariant fields of every call to one remote function / actor
    method, captured ONCE at decoration (first-call) time — the
    reference's cached serialized task-spec prefix. The serialized form
    is registered in the control-plane KV under ``template_id``; submits
    splice only per-call fields (task id, args, return ids, deadline,
    seq_no), so the hot path never re-pickles the function descriptor,
    resources, scheduling class, or owner address."""

    template_id: bytes
    kind: TaskKind
    name: str
    function_id: bytes
    num_returns: int
    resources: Dict[str, float]
    scheduling_strategy: SchedulingStrategy
    owner: Optional[Address]
    job_id: JobID
    max_retries: int = 0
    retry_exceptions: Any = False
    runtime_env: Optional[Dict[str, Any]] = None
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    max_concurrency: int = 1
    concurrency_group: Optional[str] = None

    def instantiate(
        self,
        task_id: TaskID,
        args: List[Tuple[str, Any]],
        kwargs: List[Tuple[str, str, Any]],
        return_ids: List[ObjectID],
        deadline_remaining_s: Optional[float] = None,
        seq_no: int = 0,
        trace_ctx: Optional[Tuple[str, str]] = None,
    ) -> TaskSpec:
        """Splice per-call fields into a full TaskSpec. Invariant fields
        are SHARED (same dict/strategy objects across calls) — nothing
        downstream may mutate them in place."""
        return TaskSpec(
            kind=self.kind,
            task_id=task_id,
            job_id=self.job_id,
            name=self.name,
            function_id=self.function_id,
            args=args,
            kwargs=kwargs,
            num_returns=self.num_returns,
            return_ids=return_ids,
            resources=self.resources,
            scheduling_strategy=self.scheduling_strategy,
            owner=self.owner,
            max_retries=self.max_retries,
            retry_exceptions=self.retry_exceptions,
            runtime_env=self.runtime_env,
            deadline_remaining_s=deadline_remaining_s,
            actor_id=self.actor_id,
            max_concurrency=self.max_concurrency,
            method_name=self.method_name,
            seq_no=seq_no,
            concurrency_group=self.concurrency_group,
            trace_ctx=trace_ctx,
            template_id=self.template_id,
        )

    def from_percall(self, pc: tuple) -> TaskSpec:
        return self.instantiate(
            TaskID(pc[0]),
            pc[1],
            pc[2],
            [ObjectID(b) for b in pc[3]],
            deadline_remaining_s=pc[4],
            seq_no=pc[5],
            trace_ctx=pc[6] if len(pc) > 6 else None,
        )


def encode_spec(spec: TaskSpec):
    """Wire encoding for task pushes: template-spliced specs travel as
    ``("t", template_id, per-call-tuple)``; everything else as the full
    spec (actor creation, .options() overrides, streaming)."""
    if spec.template_id is None:
        return spec
    return (
        "t",
        spec.template_id,
        (
            spec.task_id.binary(),
            spec.args,
            spec.kwargs,
            [o.binary() for o in spec.return_ids],
            spec.deadline_remaining_s,
            spec.seq_no,
            spec.trace_ctx,
        ),
    )
