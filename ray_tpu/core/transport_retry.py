"""Shared transport-retry machinery for re-pushing one logical operation.

Two call sites grew identical copies of this logic (the ordered actor
batch pump and the direct actor submit path in ``core_worker.py``), and
the pull manager's chunk retry loop needs the same backoff discipline —
this module is the single home for both pieces:

* :class:`PushBinding` — request-id reuse across re-pushes of ONE
  logical operation to a (possibly moving) server. While the binding
  targets the same client, every retry carries the SAME request id, so a
  push whose reply was lost after execution is answered from the
  server's dedup reply cache instead of running twice (``core/rpc.py``).
  A new target (the actor moved, the batch changed) is a different
  logical request and gets a fresh id.

* :func:`backoff_sleep` — jittered exponential backoff capped by the
  ambient ``core/deadline`` budget, the same discipline
  ``rpc.RpcClient.call`` applies internally, for callers that manage
  their own retry loops.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ray_tpu.core.config import GLOBAL_CONFIG


class PushBinding:
    """Tracks (target client → request id → transport-retry budget) for
    one logical push. ``bind()`` on every loop iteration: a changed
    client mints a fresh request id and resets the retry budget."""

    __slots__ = ("client", "request_id", "transport_retries")

    def __init__(self):
        self.client = None
        self.request_id: Optional[int] = None
        self.transport_retries = 0

    def bind(self, client) -> Optional[int]:
        if client is not self.client:
            self.client = client
            self.request_id = client.next_request_id()
            self.transport_retries = 0
        return self.request_id

    def invalidate(self) -> None:
        """The next push is a DIFFERENT logical request (target moved,
        payload changed): force a fresh request id on the next bind."""
        self.client = None

    def can_retry_same_target(self) -> bool:
        return self.transport_retries < GLOBAL_CONFIG.rpc_max_retries

    def note_retry(self) -> None:
        self.transport_retries += 1


def jittered_delay(attempt: int, *, base: Optional[float] = None,
                   cap: Optional[float] = None) -> float:
    """Exponential backoff delay for the Nth retry (attempt >= 1), with
    the same half-to-full jitter as the RPC client's internal loop."""
    base = base if base is not None else GLOBAL_CONFIG.rpc_retry_base_delay_s
    cap = cap if cap is not None else GLOBAL_CONFIG.rpc_retry_max_delay_s
    delay = min(base * (2 ** max(0, attempt - 1)), cap)
    return delay * (0.5 + random.random() * 0.5)


async def backoff_sleep(attempt: int, *, base: Optional[float] = None,
                        cap: Optional[float] = None) -> bool:
    """Sleep the jittered backoff for retry ``attempt``, capped by the
    ambient ``core/deadline`` budget. Returns False WITHOUT sleeping when
    the ambient budget is exhausted — the caller surfaces its last
    failure instead of sleeping into a dead deadline."""
    from ray_tpu.core.deadline import current_deadline

    delay = jittered_delay(attempt, base=base, cap=cap)
    ambient = current_deadline()
    if ambient is not None:
        remaining = ambient.remaining()
        if remaining <= 0:
            return False
        delay = min(delay, remaining)
    await asyncio.sleep(delay)
    return True
