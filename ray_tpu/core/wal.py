"""Controller write-ahead log (control-plane durability substrate).

Reference: the GCS backs its tables with a replicated store precisely
because every other recovery path recovers *through* it.  Our controller
persisted via a periodic dirty-snapshot loop, which leaves a loss window
of up to one snapshot period: a SIGKILL between ticks silently drops
every acked table mutation since the last write.  This module closes the
window with a classic WAL:

- every mutation appends one compact msgpack record *before* the RPC
  reply is sent (``Controller._wal_append``), so recovery is byte-exact
  up to the last acked mutation;
- the existing snapshot becomes a **compaction point**: after a snapshot
  commits durably, the log is atomically truncated (``WalWriter.
  truncate``) — replay-after-restart is exactly snapshot + the records
  appended since;
- records optionally carry the (client_id, request_id) dedup key and the
  pickled reply, so replay re-seeds the RPC server's exactly-once reply
  cache: a client retrying an acked mutation across a failover gets the
  cached reply, never a re-execution.

Framing is ``<crc32><len><msgpack body>`` per record; replay stops at
the first torn/corrupt frame (a crash mid-append loses only the unacked
tail — that record's reply was never sent).  Durability policy is the
``controller_wal_fsync`` knob: fsync every N appends (1 = every record,
the default), 0 = flush to the OS only (crash-of-process safe, not
crash-of-host safe).

``fsync_file_and_dir``/``durable_replace`` are shared with the snapshot
writer: the historical tmp+rename snapshot never fsynced the tmp file or
the directory entry, so a host crash could surface a zero-length "last
good snapshot".
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import Any, Iterator, Optional

import msgpack

logger = logging.getLogger(__name__)

#: per-record frame header: crc32(body), len(body)
_HDR = struct.Struct("<II")


def fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so a rename/create of the
    entry itself survives a host crash (POSIX: rename durability needs a
    directory fsync, not just the file's)."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds — best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_durable(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp + fsync(file) + rename +
    fsync(dir): the commit point is the rename, and both the bytes and
    the directory entry are on stable storage afterwards."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    durable_replace(tmp, path)


def durable_replace(tmp: str, dst: str) -> None:
    """Atomic rename-commit with directory durability. The tmp file must
    already be written and fsynced by the caller."""
    os.replace(tmp, dst)
    fsync_dir(dst)


def pack_record(record: Any) -> bytes:
    """Frame one record: header(crc, len) + msgpack body."""
    body = msgpack.packb(record, use_bin_type=True)
    return _HDR.pack(zlib.crc32(body), len(body)) + body


class WalWriter:
    """Append-only framed record log with an fsync-every-N policy and an
    atomic truncate used at snapshot compaction points."""

    def __init__(self, path: str, fsync_every: int = 1):
        self.path = path
        #: fsync every N appends; 0 disables fsync (flush only)
        self.fsync_every = fsync_every
        self._f = open(path, "ab")
        self._since_sync = 0
        #: records appended by THIS writer (not the on-disk total)
        self.appended = 0

    def append(self, record: Any) -> int:
        """Append one record and apply the fsync policy; returns the
        framed size in bytes. The record is durable (per policy) when
        this returns — callers ack only after."""
        frame = pack_record(record)
        self._f.write(frame)
        self._f.flush()
        self.appended += 1
        self._since_sync += 1
        if self.fsync_every > 0 and self._since_sync >= self.fsync_every:
            os.fsync(self._f.fileno())
            self._since_sync = 0
        return len(frame)

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def truncate(self) -> None:
        """Compaction point: atomically restart the log as empty. Uses
        the durable tmp+rename helper so a crash mid-truncate leaves
        either the old log or the new empty one, never a torn file."""
        self._f.close()
        write_durable(self.path, b"")
        self._f = open(self.path, "ab")
        self._since_sync = 0

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


def replay(path: str) -> Iterator[Any]:
    """Yield every intact record in ``path`` in append order, stopping
    cleanly at the first torn or corrupt frame (crash-truncated tail —
    by construction that record was never acked)."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    n = len(data)
    while off + _HDR.size <= n:
        crc, ln = _HDR.unpack_from(data, off)
        body = data[off + _HDR.size: off + _HDR.size + ln]
        if len(body) < ln or zlib.crc32(body) != crc:
            logger.warning(
                "WAL %s: torn tail at offset %d (%d trailing bytes dropped)",
                path, off, n - off,
            )
            return
        yield msgpack.unpackb(body, raw=False)
        off += _HDR.size + ln


def scan_tip(path: str, offset: int = 0) -> "tuple[int, int]":
    """Standby tailer: count intact records from ``offset`` without
    deserializing bodies. Returns (new_offset, records_seen) — warms the
    page cache so takeover replay reads hot data."""
    if not os.path.exists(path):
        return 0, 0
    count = 0
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if offset > size:
            offset = 0  # log truncated (compaction) — restart from head
        f.seek(offset)
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            crc, ln = _HDR.unpack_from(hdr, 0)
            body = f.read(ln)
            if len(body) < ln or zlib.crc32(body) != crc:
                break
            offset += _HDR.size + ln
            count += 1
    return offset, count


# ---- lease file (standby failover) ------------------------------------

def read_lease(path: str) -> Optional[dict]:
    """Best-effort lease read; None if absent/torn (writers use atomic
    tmp+rename so torn reads only happen on exotic filesystems)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
        return msgpack.unpackb(data, raw=False)
    except Exception:
        return None


def write_lease(path: str, *, epoch: int, port: int, pid: int, ts: float) -> None:
    """Atomic lease stamp. No fsync: the lease is a liveness signal, not
    durable state — a host crash invalidates it by going silent anyway."""
    tmp = path + f".tmp.{pid}"
    with open(tmp, "wb") as f:
        f.write(msgpack.packb(
            {"epoch": epoch, "port": port, "pid": pid, "ts": ts},
            use_bin_type=True,
        ))
    os.replace(tmp, path)
