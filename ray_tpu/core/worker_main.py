"""Worker process entrypoint (spawned by the node daemon).

Reference: the default worker main loop
(``python/ray/_private/workers/default_worker.py`` + ``run_task_loop``
``_raylet.pyx:3387``). The process builds a CoreWorker + TaskExecutor,
registers with its node daemon using the spawn token, then parks — all
work arrives over RPC on the io thread.
"""

from __future__ import annotations

import logging
import os
import signal
import threading


def main() -> None:
    import faulthandler

    faulthandler.enable()
    faulthandler.register(signal.SIGUSR2, all_threads=True)
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    token = os.environ["RAY_TPU_SPAWN_TOKEN"]
    chost, cport = os.environ["RAY_TPU_CONTROLLER_ADDR"].rsplit(":", 1)
    dhost, dport = os.environ["RAY_TPU_DAEMON_ADDR"].rsplit(":", 1)

    from ray_tpu.core import api
    from ray_tpu.core.core_worker import CoreWorker
    from ray_tpu.core.ids import JobID
    from ray_tpu.core.task_executor import TaskExecutor

    executor = TaskExecutor()
    core = CoreWorker(chost, int(cport), dhost, int(dport), executor=executor)
    worker = api.Worker(api.Worker.MODE_WORKER, core, JobID.nil(), namespace="")
    api.set_global_worker(worker)
    executor.bind(core, worker)
    # Bind fully BEFORE registering: the daemon may dispatch work (e.g.
    # actor creation) the moment registration lands.
    reply = core.io.run(
        core.daemon.call(
            "register_worker",
            {"token": token, "host": core.host, "port": core.port},
            retries=5,
        )
    )
    core.finish_init(reply["node_id"])
    worker.address = core.address

    from ray_tpu.observability.timeline import start_export_thread

    start_export_thread()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())

    # Orphan defense (hang defense layer): if the spawning daemon dies
    # without reaping us (SIGKILL'd, crashed), this process would park on
    # stop.wait() forever holding ports/shm — exactly the leaked
    # `worker_main` class from the round-5 verdict. Reparenting is the
    # tell — compared against the pid the DAEMON stamped at spawn, not a
    # boot-time os.getppid() (the daemon can die while we are still
    # importing, and we would memorize the already-reparented value).
    daemon_pid = int(os.environ.get("RAY_TPU_DAEMON_PID", 0)) or os.getppid()

    def _orphan_watch() -> None:
        import time as _time

        while not stop.is_set():
            if os.getppid() != daemon_pid:
                logging.getLogger(__name__).warning(
                    "node daemon (pid %d) is gone; worker exiting", daemon_pid
                )
                os._exit(0)
            _time.sleep(1.0)

    threading.Thread(target=_orphan_watch, daemon=True, name="orphan-watch").start()
    stop.wait()
    os._exit(0)


if __name__ == "__main__":
    main()
