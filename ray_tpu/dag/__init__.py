"""Compiled graphs (aDAG): static actor DAGs over mutable shm channels.

Reference: ``python/ray/dag/`` + ``python/ray/experimental/channel/``.

Usage::

    with InputNode() as inp:
        dag = stage2.forward.bind(stage1.forward.bind(inp))
    compiled = dag.experimental_compile()
    out = compiled.execute(x).get()
    compiled.teardown()
"""

from ray_tpu.dag.channel import ChannelClosedError, ChannelTimeoutError, ShmChannel
from ray_tpu.dag.collective import CollectiveOutputNode, allreduce
from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.node import (
    ActorClassNode,
    ActorMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "ActorClassNode",
    "ActorMethodNode",
    "CollectiveOutputNode",
    "allreduce",
    "ChannelClosedError",
    "ChannelTimeoutError",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "FunctionNode",
    "InputAttributeNode",
    "InputNode",
    "MultiOutputNode",
    "ShmChannel",
]
