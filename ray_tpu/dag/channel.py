"""Mutable shared-memory channels — the transport under compiled graphs.

Reference: ``src/ray/core_worker/experimental_mutable_object_manager.h:48``
and ``python/ray/experimental/channel/shared_memory_channel.py`` — mutable
(versioned) shm objects with writer/reader acquire semantics and timeouts,
reused across DAG executions so the per-execution cost is a memcpy + a
version bump instead of an object-store allocation and RPC.

TPU-native redesign: one POSIX shm segment per channel holding a small
ring of slots (seqlock-style versioning, per-reader consume cursors in the
header). Writers block when the ring is full (backpressure = ring depth);
readers block on the slot version. All coordination is in shared memory —
zero RPCs on the steady-state path. Cross-host channels are intentionally
NOT built on this layer: on TPU the inter-host data path belongs to the
in-program ICI collectives (``parallel/``), not the actor channel layer.

Layout (little-endian):
    [u32 magic][u32 num_slots][u64 slot_size][u32 num_readers][u32 pad]
    [u64 reader_cursor] * num_readers        # next seq each reader wants
    slot * num_slots, each:
        [u64 version]    # seq+1 once the write of that seq is complete
        [u64 length]
        [payload bytes]

A value is framed with a 1-byte kind: 0=value, 1=error (pickled
exception), 2=close (teardown sentinel).
"""

from __future__ import annotations

import ctypes
import pickle
import struct
import time
from typing import List, Optional, Tuple

_MAGIC = 0x52544348  # "RTCH"
_HDR = struct.Struct("<IIQII")
_U64 = struct.Struct("<Q")
_SLOT_HDR = struct.Struct("<QQ")

KIND_VALUE = 0
KIND_ERROR = 1
KIND_CLOSE = 2


# ---------------------------------------------------------------------------
# POSIX named semaphores (ctypes): the cross-process wakeup primitive.
# Sleep-polling costs ~0.5-2ms per handoff on a loaded host; sem_post/
# sem_timedwait make channel handoffs kernel-scheduled. glibc puts named
# semaphores in /dev/shm as ``sem.<name>`` — same namespace discipline as
# the channel segments, so orphan sweeps can reap both.

_libc = ctypes.CDLL(None, use_errno=True)
_SEM_FAILED = ctypes.c_void_p(-1).value
_O_CREAT = 0o100


class _timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


try:
    _libc.sem_open.restype = ctypes.c_void_p
    _libc.sem_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint, ctypes.c_uint]
    _libc.sem_post.argtypes = [ctypes.c_void_p]
    _libc.sem_timedwait.argtypes = [ctypes.c_void_p, ctypes.POINTER(_timespec)]
    _libc.sem_trywait.argtypes = [ctypes.c_void_p]
    _libc.sem_close.argtypes = [ctypes.c_void_p]
    _HAVE_SEM = True
except AttributeError:  # non-glibc platform: fall back to pure polling
    _HAVE_SEM = False


class _Sem:
    """A named semaphore used as a wakeup HINT — shm versions/cursors stay
    authoritative, so lost or extra posts are harmless."""

    def __init__(self, name: str):
        self.name = name
        self._h = None
        if not _HAVE_SEM:
            return
        h = _libc.sem_open(("/" + name).encode(), _O_CREAT, 0o600, 0)
        if h != _SEM_FAILED:
            self._h = h

    def post(self) -> None:
        if self._h is not None:
            _libc.sem_post(self._h)

    def wait(self, timeout_s: float) -> None:
        """Block up to ``timeout_s`` for a post (spurious returns fine)."""
        if self._h is None:
            time.sleep(min(timeout_s, 0.0005))
            return
        now = time.time() + timeout_s
        ts = _timespec(int(now), int((now % 1.0) * 1e9))
        _libc.sem_timedwait(self._h, ctypes.byref(ts))

    def drain(self) -> None:
        if self._h is None:
            return
        while _libc.sem_trywait(self._h) == 0:
            pass

    def close(self) -> None:
        if self._h is not None:
            _libc.sem_close(self._h)
            self._h = None

    @staticmethod
    def unlink(name: str) -> None:
        if _HAVE_SEM:
            _libc.sem_unlink(("/" + name).encode())


class ChannelTimeoutError(TimeoutError):
    """A channel read/write did not complete within the timeout
    (reference ``RayChannelTimeoutError``)."""


class ChannelClosedError(RuntimeError):
    """The peer tore the compiled graph down."""


# one tracker-workaround implementation, shared with the object store
from ray_tpu.core.object_store import _attach, _create  # noqa: E402


def _check_deadline(deadline: Optional[float]) -> None:
    if deadline is not None and time.monotonic() > deadline:
        raise ChannelTimeoutError("channel operation timed out")


class ShmChannel:
    """One ring-buffer channel. The creator (driver) owns the segment
    lifetime; actors attach by name."""

    def __init__(
        self,
        name: str,
        *,
        create: bool = False,
        slot_size: int = 1 << 20,
        num_slots: int = 8,
        num_readers: int = 1,
    ):
        from ray_tpu.core.object_store import ensure_scrubbed_tracker

        ensure_scrubbed_tracker()
        self.name = name
        if create:
            total = _HDR.size + 8 * num_readers + num_slots * (_SLOT_HDR.size + slot_size)
            self._seg = _create(name, total)
            self._buf = memoryview(self._seg.buf)
            _HDR.pack_into(self._buf, 0, _MAGIC, num_slots, slot_size, num_readers, 0)
            for i in range(num_readers):
                _U64.pack_into(self._buf, _HDR.size + 8 * i, 0)
            for s in range(num_slots):
                _SLOT_HDR.pack_into(self._buf, self._slot_off_static(s, num_readers, slot_size), 0, 0)
        else:
            self._seg = _attach(name)
            self._buf = memoryview(self._seg.buf)
            magic, num_slots, slot_size, num_readers, _ = _HDR.unpack_from(self._buf, 0)
            if magic != _MAGIC:
                raise ValueError(f"{name} is not a channel segment")
        self.slot_size = slot_size
        self.num_slots = num_slots
        self.num_readers = num_readers
        self._cursor_base = _HDR.size
        self._slots_base = _HDR.size + 8 * num_readers
        # wakeup hints: one sem per reader (posted on write), one for the
        # writer (posted on advance)
        self._reader_sems: List[_Sem] = [
            _Sem(f"{name}-r{i}") for i in range(num_readers)
        ]
        self._writer_sem = _Sem(f"{name}-w")

    @staticmethod
    def _slot_off_static(slot: int, num_readers: int, slot_size: int) -> int:
        return _HDR.size + 8 * num_readers + slot * (_SLOT_HDR.size + slot_size)

    def _slot_off(self, slot: int) -> int:
        return self._slots_base + slot * (_SLOT_HDR.size + self.slot_size)

    # -- writer ----------------------------------------------------------
    def _min_cursor(self) -> int:
        lo = None
        for i in range(self.num_readers):
            (c,) = _U64.unpack_from(self._buf, self._cursor_base + 8 * i)
            lo = c if lo is None else min(lo, c)
        return lo or 0

    def write(self, seq: int, kind: int, payload: bytes, timeout: Optional[float] = None) -> None:
        """Publish ``payload`` as execution ``seq``. Blocks while the slot
        still holds an unconsumed previous value (ring backpressure)."""
        if len(payload) + 1 > self.slot_size:
            raise ValueError(
                f"value of {len(payload)} bytes exceeds channel slot size "
                f"{self.slot_size}; recompile with a larger _buffer_size_bytes"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        # slot is free once every reader has consumed its previous tenant
        # (seq - num_slots); i.e. all cursors are past it
        while self._min_cursor() < seq - self.num_slots + 1:
            _check_deadline(deadline)
            self._writer_sem.wait(0.05)
        off = self._slot_off(seq % self.num_slots)
        body_off = off + _SLOT_HDR.size
        self._buf[body_off] = kind
        self._buf[body_off + 1 : body_off + 1 + len(payload)] = payload
        # Length then version: the version word is what readers poll.
        # ORDERING CAVEAT: these are plain memoryview stores with no
        # explicit release fence — correctness relies on x86-TSO (stores
        # retire in program order). On a weakly-ordered host (ARM) a
        # reader could observe version==seq+1 before the payload stores
        # and deserialize torn data; porting there needs an atomic
        # release write (or a payload checksum in the slot header).
        # TPU-host fleets are x86, so this build documents rather than
        # pays the fence cost.
        _SLOT_HDR.pack_into(self._buf, off, 0, len(payload) + 1)
        _U64.pack_into(self._buf, off, seq + 1)
        for sem in self._reader_sems:
            sem.post()

    def write_value(self, seq: int, value, timeout: Optional[float] = None) -> None:
        from ray_tpu.core import serialization

        self.write(seq, KIND_VALUE, serialization.serialize(value).to_bytes(), timeout)

    def write_error(self, seq: int, error: BaseException, timeout: Optional[float] = None) -> None:
        self.write(seq, KIND_ERROR, pickle.dumps(error), timeout)

    def write_close(self, seq: int, timeout: Optional[float] = None) -> None:
        self.write(seq, KIND_CLOSE, b"", timeout)

    # -- reader ----------------------------------------------------------
    def read(self, reader: int, seq: int, timeout: Optional[float] = None) -> Tuple[int, memoryview]:
        """Return (kind, payload_view) for ``seq``. The view aliases the
        slot — call :meth:`advance` only after the value is consumed (the
        slot is never overwritten before every cursor passes it)."""
        off = self._slot_off(seq % self.num_slots)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            (version,) = _U64.unpack_from(self._buf, off)
            if version == seq + 1:
                break
            _check_deadline(deadline)
            self._reader_sems[reader].wait(0.05)
        (_, length) = _SLOT_HDR.unpack_from(self._buf, off)
        body_off = off + _SLOT_HDR.size
        kind = self._buf[body_off]
        return kind, self._buf[body_off + 1 : body_off + length]

    def read_value(self, reader: int, seq: int, timeout: Optional[float] = None):
        """Read + decode ``seq``; raises on error/close markers. The
        decoded value may alias slot memory — consume before advance."""
        from ray_tpu.core import serialization

        kind, view = self.read(reader, seq, timeout)
        if kind == KIND_CLOSE:
            raise ChannelClosedError("channel closed")
        if kind == KIND_ERROR:
            raise pickle.loads(view)
        return serialization.deserialize_bytes(view)

    def advance(self, reader: int, seq: int) -> None:
        """Mark ``seq`` consumed by ``reader`` — frees the slot for reuse
        once all readers pass it."""
        _U64.pack_into(self._buf, self._cursor_base + 8 * reader, seq + 1)
        self._writer_sem.post()

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        for sem in self._reader_sems:
            sem.close()
        self._writer_sem.close()
        try:
            self._buf.release()
        except Exception:
            pass
        try:
            self._seg.close()
        except Exception:
            pass

    def unlink(self) -> None:
        for i in range(self.num_readers):
            _Sem.unlink(f"{self.name}-r{i}")
        _Sem.unlink(f"{self.name}-w")
        try:
            self._seg.unlink()
        except Exception:
            pass
