"""DAG collective nodes: allreduce across compiled-graph actors.

Reference: ``python/ray/dag/collective_node.py:127`` +
``experimental/collective/allreduce.py`` — N upstream nodes (one per
actor) feed one logical collective; every actor receives the reduced
value locally. The reference transports over NCCL; the TPU-native
backend is the object-store relay group (``parallel/collectives.py``) —
cross-PROCESS dense reduction on TPU hosts rides DCN/shm, while
intra-program reductions belong to XLA collectives (``parallel/``),
not the DAG layer.

    with InputNode() as inp:
        s1 = a1.shard.bind(inp)
        s2 = a2.shard.bind(inp)
        r1, r2 = allreduce.bind([s1, s2], op="sum")
        dag = MultiOutputNode([r1, r2])
"""

from __future__ import annotations

import uuid
from typing import List

from ray_tpu.dag.node import ActorMethodNode, DAGNode


class CollectiveOutputNode(DAGNode):
    """Rank ``rank``'s output of one logical allreduce."""

    def __init__(self, group_uid: str, upstream: ActorMethodNode, op: str,
                 world_size: int, rank: int):
        self.group_uid = group_uid
        self.upstream = upstream
        self.op = op
        self.world_size = world_size
        self.rank = rank
        # the collective executes IN the upstream node's actor
        self.handle = upstream.handle

    def _upstream(self) -> List[DAGNode]:
        return [self.upstream]


class _AllReduce:
    def bind(self, nodes: List[ActorMethodNode], op: str = "sum") -> List[CollectiveOutputNode]:
        if len(nodes) < 2:
            raise ValueError("allreduce needs >=2 participating nodes")
        actors = set()
        for n in nodes:
            if not isinstance(n, ActorMethodNode):
                raise TypeError(
                    "allreduce participants must be actor-method nodes"
                )
            aid = n.handle.actor_id.binary()
            if aid in actors:
                raise ValueError(
                    "allreduce participants must live on DISTINCT actors "
                    "(one rank per process)"
                )
            actors.add(aid)
        uid = uuid.uuid4().hex[:12]
        return [
            CollectiveOutputNode(uid, n, op, len(nodes), i)
            for i, n in enumerate(nodes)
        ]


allreduce = _AllReduce()
