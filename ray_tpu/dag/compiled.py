"""Compiled execution of actor DAGs over mutable shm channels.

Reference: ``python/ray/dag/compiled_dag_node.py`` — ``CompiledDAG``
(``:135``), per-actor ``ExecutableTask`` loops (``:349``, ``:668``), the
driver proxy (``:679``) and ``execute`` (``:2065``). A static DAG of actor
method calls is compiled ONCE into: (a) a set of ring-buffer shm channels
(``channel.py``), one per cross-process edge, and (b) one long-running
loop per actor that reads its input channels, runs the bound methods, and
writes its outputs — so steady-state executions cost shm memcpys and
version bumps, with no RPC, no task submission, and no object store on
the hot path.

TPU mapping (SURVEY §5.8): shm channels are unchanged from the reference
design; the GPU NCCL channel (``torch_tensor_nccl_channel.py``) has NO
analogue here because on TPU device-to-device movement belongs to XLA
collectives inside one jitted program (``parallel/``) — a compiled actor
pipeline stages host arrays through shm and each actor re-uploads to its
own chip, which is the correct topology for PP-style serving where stages
own disjoint devices.
"""

from __future__ import annotations

import logging
import pickle
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.channel import (
    KIND_CLOSE,
    KIND_ERROR,
    KIND_VALUE,
    ChannelClosedError,
    ShmChannel,
)
from ray_tpu.dag.collective import CollectiveOutputNode
from ray_tpu.dag.node import (
    ActorMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

logger = logging.getLogger(__name__)

DAG_LOOP_METHOD = "__ray_dag_loop__"


# ---------------------------------------------------------------------------
# classic (uncompiled) execution


def execute_classic(root: DAGNode, args: Tuple, kwargs: Dict):
    """One ``.remote()`` per node; ObjectRefs flow as arguments so the
    runtime's normal dependency machinery does the rest."""
    memo: Dict[int, Any] = {}

    def resolve(node):
        if not isinstance(node, DAGNode):
            return node
        key = id(node)
        if key in memo:
            return memo[key]
        if isinstance(node, InputNode):
            if kwargs or len(args) != 1:
                raise ValueError(
                    "multi-arg DAG input requires accessors (inp[i] / inp.key)"
                )
            out = args[0]
        elif isinstance(node, InputAttributeNode):
            out = (
                args[node.key]
                if isinstance(node.key, int)
                else kwargs[node.key]
            )
        elif isinstance(node, MultiOutputNode):
            out = [resolve(o) for o in node.outputs]
        elif isinstance(node, FunctionNode):
            rargs = [resolve(a) for a in node.args]
            rkwargs = {k: resolve(v) for k, v in node.kwargs.items()}
            out = node.remote_fn.remote(*rargs, **rkwargs)
        elif isinstance(node, ActorMethodNode):
            rargs = [resolve(a) for a in node.args]
            rkwargs = {k: resolve(v) for k, v in node.kwargs.items()}
            out = getattr(node.handle, node.method_name).remote(*rargs, **rkwargs)
        else:
            raise TypeError(f"cannot execute node type {type(node).__name__}")
        memo[key] = out
        return out

    try:
        return resolve(root)
    finally:
        # Break the recursive closure's self-cycle (cell → resolve →
        # cell): left intact it pins the node graph — and the actor
        # HANDLES inside it — until a generational GC pass, deferring
        # handle-drop actor reclamation unboundedly.
        resolve = None


# ---------------------------------------------------------------------------
# compiled execution


class CompiledDAGRef:
    """Result handle for one ``execute()`` (reference ``CompiledDAGRef``).
    Results must be retrieved via :meth:`get` (or ``ray_tpu.get``)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._consumed = False

    def get(self, timeout: Optional[float] = None):
        if self._consumed:
            raise ValueError("CompiledDAGRef results can only be retrieved once")
        self._consumed = True
        return self._dag._get_result(self._seq, timeout)

    def __del__(self):
        # a dropped, never-got ref must not pin its cached result forever
        if not getattr(self, "_consumed", True):
            try:
                self._dag._discard_result(self._seq)
            except Exception:
                pass


class _ChannelSpec:
    __slots__ = ("name", "slot_size", "num_slots", "readers")

    def __init__(self, name, slot_size, num_slots):
        self.name = name
        self.slot_size = slot_size
        self.num_slots = num_slots
        self.readers: List[Any] = []  # consumer identities (actor_id bytes | "driver")

    def reader_idx(self, who) -> int:
        return self.readers.index(who)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "slot_size": self.slot_size,
            "num_slots": self.num_slots,
            "num_readers": max(1, len(self.readers)),
        }


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int, max_inflight: int, timeout_s: float):
        self._root = root
        self._buffer = buffer_size_bytes
        self._slots = max(2, max_inflight)
        self._timeout = timeout_s
        self._seq = 0
        self._next_get = 0
        self._result_cache: Dict[int, Any] = {}
        self._discarded: set = set()
        self._lock = threading.Lock()
        self._exec_lock = threading.Lock()  # serializes input writes
        self._torn_down = False
        self._compile()

    # -- compilation -----------------------------------------------------
    def _compile(self) -> None:
        outputs = (
            self._root.outputs if isinstance(self._root, MultiOutputNode) else [self._root]
        )
        # topo order over the actor-method nodes
        order: List[DAGNode] = []
        seen: Dict[int, bool] = {}
        has_input = False

        def visit(node: DAGNode):
            nonlocal has_input
            if id(node) in seen:
                return
            seen[id(node)] = True
            if isinstance(node, (InputNode, InputAttributeNode)):
                has_input = True
                return
            if isinstance(node, FunctionNode):
                raise ValueError(
                    "compiled graphs support actor methods only; "
                    "fn.bind(...) nodes require classic execute()"
                )
            if not isinstance(node, (ActorMethodNode, CollectiveOutputNode)):
                raise TypeError(f"cannot compile node type {type(node).__name__}")
            for up in node._upstream():
                visit(up)
            order.append(node)

        try:
            for out in outputs:
                if isinstance(out, (InputNode, InputAttributeNode)):
                    raise ValueError("a compiled DAG output must be an actor method")
                visit(out)
        finally:
            visit = None  # break the recursive closure's self-cycle
        if not has_input:
            raise ValueError("compiled DAGs must consume an InputNode")

        # pid in the name lets the daemon's orphan sweep reap channels
        # (and their sem.* wakeup files) of crashed drivers
        import os

        run_id = f"{os.getpid()}-{uuid.uuid4().hex[:10]}"
        self._input_chan_spec = _ChannelSpec(f"rt-chan-{run_id}-in", self._buffer, self._slots)
        chan_of: Dict[int, _ChannelSpec] = {}  # producing node id -> channel
        n_chan = 0

        def actor_of(node):
            return node.handle.actor_id.binary()

        # a node needs a channel iff some consumer lives in another process
        consumers: Dict[int, List[Any]] = {id(n): [] for n in order}
        for node in order:
            for up in node._upstream():
                if isinstance(up, (ActorMethodNode, CollectiveOutputNode)):
                    consumers[id(up)].append(actor_of(node))
                elif isinstance(up, (InputNode, InputAttributeNode)):
                    if actor_of(node) not in self._input_chan_spec.readers:
                        self._input_chan_spec.readers.append(actor_of(node))
        for out in outputs:
            consumers[id(out)].append("driver")

        # every rank of an allreduce must be reachable from the outputs:
        # a missing rank's actor never runs its collective op and the
        # present ranks HANG in the rendezvous (reference raises too)
        ranks_present: Dict[str, int] = {}
        world_of: Dict[str, int] = {}
        for node in order:
            if isinstance(node, CollectiveOutputNode):
                ranks_present[node.group_uid] = ranks_present.get(node.group_uid, 0) + 1
                world_of[node.group_uid] = node.world_size
        for uid, present in ranks_present.items():
            if present != world_of[uid]:
                raise ValueError(
                    f"allreduce group {uid}: only {present}/{world_of[uid]} "
                    "ranks are reachable from the DAG outputs — consume "
                    "every CollectiveOutputNode (unreferenced ranks would "
                    "deadlock the rendezvous)"
                )

        # tensor-transport contract: a "device" producer must never need
        # a cross-process channel (TPU has no device IPC; see
        # DAGNode.with_tensor_transport)
        for node in order:
            if getattr(node, "transport", "auto") == "device":
                remote = [c for c in consumers[id(node)] if c != actor_of(node)]
                if remote:
                    raise ValueError(
                        f"node {getattr(node, 'method_name', node)!r} is "
                        "annotated with_tensor_transport('device') but has "
                        "consumers in other processes — TPU device buffers "
                        "cannot cross processes; keep the pipeline stage on "
                        "one actor or use XLA collectives (parallel/) for "
                        "cross-chip movement"
                    )

        for node in order:
            remote = [c for c in consumers[id(node)] if c != actor_of(node)]
            if remote:
                spec = _ChannelSpec(f"rt-chan-{run_id}-{n_chan}", self._buffer, self._slots)
                n_chan += 1
                for c in remote:
                    if c not in spec.readers:
                        spec.readers.append(c)
                chan_of[id(node)] = spec

        # build per-actor plans
        plans: Dict[bytes, Dict[str, Any]] = {}
        local_ids: Dict[int, int] = {}
        for i, node in enumerate(order):
            local_ids[id(node)] = i
        for node in order:
            aid = actor_of(node)
            plan = plans.setdefault(aid, {"ops": [], "chans": {}})

            def argspec(a):
                if isinstance(a, (InputNode, InputAttributeNode)):
                    spec = self._input_chan_spec
                    d = spec.as_dict()
                    d["reader_idx"] = spec.reader_idx(aid)
                    plan["chans"][spec.name] = d
                    key = a.key if isinstance(a, InputAttributeNode) else None
                    return ("chan", spec.name, key)
                if isinstance(a, (ActorMethodNode, CollectiveOutputNode)):
                    if actor_of(a) == aid:
                        return ("local", local_ids[id(a)])
                    spec = chan_of[id(a)]
                    d = spec.as_dict()
                    d["reader_idx"] = spec.reader_idx(aid)
                    plan["chans"][spec.name] = d
                    return ("chan", spec.name, None)
                if isinstance(a, DAGNode):
                    raise TypeError(f"unsupported arg node {type(a).__name__}")
                return ("const", pickle.dumps(a))

            out_spec = chan_of.get(id(node))
            if isinstance(node, CollectiveOutputNode):
                plan["ops"].append(
                    {
                        "method": None,
                        "collective": {
                            "group": f"dag-{run_id}-{node.group_uid}",
                            "world": node.world_size,
                            "rank": node.rank,
                            "op": node.op,
                        },
                        "args": [argspec(node.upstream)],
                        "kwargs": {},
                        "local_id": local_ids[id(node)],
                        "out": out_spec.as_dict() if out_spec else None,
                    }
                )
            else:
                plan["ops"].append(
                    {
                        "method": node.method_name,
                        "args": [argspec(a) for a in node.args],
                        "kwargs": {k: argspec(v) for k, v in node.kwargs.items()},
                        "local_id": local_ids[id(node)],
                        "out": out_spec.as_dict() if out_spec else None,
                    }
                )

        # driver-side channel objects (create them all here — actors attach)
        self._input_chan = ShmChannel(
            self._input_chan_spec.name,
            create=True,
            slot_size=self._buffer,
            num_slots=self._slots,
            num_readers=max(1, len(self._input_chan_spec.readers)),
        )
        self._all_chans: List[ShmChannel] = [self._input_chan]
        self._out_readers: List[Tuple[ShmChannel, int]] = []
        created: Dict[str, ShmChannel] = {self._input_chan_spec.name: self._input_chan}
        for node in order:
            spec = chan_of.get(id(node))
            if spec is None:
                continue
            ch = ShmChannel(
                spec.name,
                create=True,
                slot_size=spec.slot_size,
                num_slots=spec.num_slots,
                num_readers=max(1, len(spec.readers)),
            )
            created[spec.name] = ch
            self._all_chans.append(ch)
        for out in outputs:
            spec = chan_of[id(out)]
            self._out_readers.append((created[spec.name], spec.reader_idx("driver")))
        self._multi = isinstance(self._root, MultiOutputNode)

        # launch the loops (one long-running actor task per actor)
        self._loop_refs = []
        self._handles = {}
        for node in order:
            aid = actor_of(node)
            self._handles[aid] = node.handle
        for aid, plan in plans.items():
            self._loop_refs.append(self._submit_loop(self._handles[aid], plan))

    def _submit_loop(self, handle, plan):
        from ray_tpu.core.actor import ActorMethod

        return ActorMethod(handle, DAG_LOOP_METHOD, {}).remote(plan)

    # -- execution -------------------------------------------------------
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        from ray_tpu.core import serialization

        payload = serialization.serialize((args, kwargs)).to_bytes()
        # The seq is committed only once the write SUCCEEDS (the lock
        # covers both): a failed write (oversized value, backpressure
        # timeout) must not leave a hole in the strictly-sequential
        # stream — the loops would wait on that slot forever.
        with self._exec_lock:
            with self._lock:
                if self._torn_down:
                    raise RuntimeError("this compiled DAG has been torn down")
                seq = self._seq
            self._input_chan.write(seq, KIND_VALUE, payload, timeout=self._timeout)
            with self._lock:
                self._seq += 1
        return CompiledDAGRef(self, seq)

    def _discard_result(self, seq: int) -> None:
        with self._lock:
            if seq < self._next_get:
                self._result_cache.pop(seq, None)
            else:
                self._discarded.add(seq)

    def _get_result(self, seq: int, timeout: Optional[float]):
        timeout = self._timeout if timeout is None else timeout
        with self._lock:
            while self._next_get <= seq:
                cur = self._next_get
                outs: List[Any] = []
                err: Optional[BaseException] = None
                raw: List[Any] = []
                for ch, ridx in self._out_readers:
                    kind, view = ch.read(ridx, cur, timeout)
                    # copy BEFORE advancing: the decoded value would
                    # otherwise alias the slot, which the writer may
                    # overwrite once the cursor moves
                    raw.append((kind, bytes(view)))
                for ch, ridx in self._out_readers:
                    ch.advance(ridx, cur)
                from ray_tpu.core import serialization

                for kind, data in raw:
                    if kind == KIND_CLOSE:
                        raise ChannelClosedError("compiled DAG torn down")
                    if kind == KIND_ERROR:
                        e = pickle.loads(data)
                        err = err or e
                        outs.append(e)
                    else:
                        outs.append(serialization.deserialize_bytes(data))
                if cur in self._discarded:
                    self._discarded.discard(cur)
                else:
                    self._result_cache[cur] = err if err is not None else (
                        outs if self._multi else outs[0]
                    )
                self._next_get = cur + 1
            result = self._result_cache.pop(seq)
        if isinstance(result, BaseException):
            raise result
        return result

    # -- teardown --------------------------------------------------------
    def teardown(self) -> None:
        with self._exec_lock:
            with self._lock:
                if self._torn_down:
                    return
                self._torn_down = True
                seq = self._seq
                self._seq += 1
            try:
                self._input_chan.write_close(seq, timeout=self._timeout)
            except Exception:
                logger.debug("close write failed during teardown", exc_info=True)
        import ray_tpu

        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=self._timeout)
            except Exception:
                logger.debug("loop did not exit cleanly", exc_info=True)
        for ch in self._all_chans:
            ch.unlink()
            ch.close()
        # drop graph/handle references NOW: actor reclamation is driven by
        # handle refcounts, and a compiled dag must not pin its actors
        # past teardown
        self._root = None
        self._handles = {}
        self._loop_refs = []
        self._out_readers = []
        self._all_chans = []

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# worker-side loop (runs inside the actor's execution lane)


import os as _os


def _chan_alive(ch: ShmChannel) -> bool:
    return _os.path.exists("/dev/shm/" + ch.name)


def _read_live(ch: ShmChannel, reader: int, seq: int):
    """Read with a liveness check: a blocked read must notice when the
    driver unlinked the channel (teardown after abandoning results, or a
    crashed driver) instead of pinning the actor's lane forever."""
    from ray_tpu.dag.channel import ChannelTimeoutError

    while True:
        try:
            return ch.read(reader, seq, timeout=5.0)
        except ChannelTimeoutError:
            if not _chan_alive(ch):
                raise ChannelClosedError(f"channel {ch.name} unlinked")


def _write_live(write_fn, ch: ShmChannel, *args) -> None:
    """Write with the same liveness rule (ring backpressure against a
    gone driver must not wedge the loop)."""
    from ray_tpu.dag.channel import ChannelTimeoutError

    while True:
        try:
            return write_fn(*args, timeout=5.0)
        except ChannelTimeoutError:
            if not _chan_alive(ch):
                raise ChannelClosedError(f"channel {ch.name} unlinked")


def run_dag_loop(actor_instance, plan: Dict[str, Any]) -> None:
    """The compiled per-actor loop (reference ``ExecutableTask`` loops,
    ``compiled_dag_node.py:668``): attach channels once, then read →
    compute → write until a CLOSE marker cascades through."""
    chans: Dict[str, ShmChannel] = {}
    reader_idx: Dict[str, int] = {}
    for name, d in plan["chans"].items():
        chans[name] = ShmChannel(name)
        reader_idx[name] = d["reader_idx"]
    out_chans: Dict[str, ShmChannel] = {}
    for op in plan["ops"]:
        if op["out"] is not None and op["out"]["name"] not in out_chans:
            out_chans[op["out"]["name"]] = ShmChannel(op["out"]["name"])
    consts: Dict[int, Any] = {}
    coll_groups: Dict[str, Any] = {}  # lazy per-loop collective groups

    from ray_tpu.core import serialization

    seq = 0
    try:
        while True:
            # read every input channel once for this seq
            views: Dict[str, Tuple[int, Any]] = {}
            closing = False
            for name, ch in chans.items():
                kind, view = _read_live(ch, reader_idx[name], seq)
                views[name] = (kind, view)
                if kind == KIND_CLOSE:
                    closing = True
            if closing:
                for ch in out_chans.values():
                    try:
                        ch.write(seq, KIND_CLOSE, b"", timeout=5)
                    except Exception:
                        pass
                return
            error: Optional[BaseException] = None
            local_vals: Dict[int, Any] = {}
            decoded: Dict[str, Any] = {}
            plan_input_name = next(
                (n for n in plan["chans"] if n.endswith("-in")), None
            )

            def resolve(spec):
                kind = spec[0]
                if kind == "const":
                    key = id(spec[1])
                    if key not in consts:
                        consts[key] = pickle.loads(spec[1])
                    return consts[key]
                if kind == "local":
                    return local_vals[spec[1]]
                # ("chan", name, accessor)
                _, name, accessor = spec
                if name not in decoded:
                    k, view = views[name]
                    if k == KIND_ERROR:
                        raise pickle.loads(view)
                    decoded[name] = serialization.deserialize_bytes(view)
                value = decoded[name]
                if name == plan_input_name:
                    in_args, in_kwargs = value
                    if accessor is None:
                        if in_kwargs or len(in_args) != 1:
                            raise ValueError(
                                "multi-arg input consumed without accessor"
                            )
                        return in_args[0]
                    if isinstance(accessor, int):
                        return in_args[accessor]
                    return in_kwargs[accessor]
                return value

            for op in plan["ops"]:
                try:
                    if error is not None:
                        raise error
                    args = [resolve(s) for s in op["args"]]
                    kwargs = {k: resolve(s) for k, s in op["kwargs"].items()}
                    coll = op.get("collective")
                    if coll is not None:
                        # DAG allreduce (reference collective_node.py:127)
                        # over the object-store relay group
                        group = coll_groups.get(coll["group"])
                        if group is None:
                            from ray_tpu.parallel.collectives import CollectiveGroup

                            group = coll_groups[coll["group"]] = CollectiveGroup(
                                coll["group"], coll["world"], coll["rank"]
                            )
                        result = group.allreduce(args[0], op=coll["op"])
                    else:
                        result = getattr(actor_instance, op["method"])(*args, **kwargs)
                    local_vals[op["local_id"]] = result
                    if op["out"] is not None:
                        ch = out_chans[op["out"]["name"]]
                        _write_live(ch.write_value, ch, seq, result)
                except ChannelClosedError:
                    return  # driver gone / torn down: exit the loop
                except BaseException as e:  # noqa: BLE001 — propagate per-seq
                    error = error or e
                    if op["out"] is not None:
                        try:
                            ch = out_chans[op["out"]["name"]]
                            _write_live(ch.write_error, ch, seq, e)
                        except ChannelClosedError:
                            return
                        except Exception:
                            pass
            # consume AFTER compute: slot views must stay valid while the
            # methods run (zero-copy reads)
            for name, ch in chans.items():
                ch.advance(reader_idx[name], seq)
            seq += 1
    except ChannelClosedError:
        return  # teardown unlinked the channels / driver died
    finally:
        for ch in list(chans.values()) + list(out_chans.values()):
            ch.close()
