"""DAG node types and classic (uncompiled) execution.

Reference: ``python/ray/dag/dag_node.py`` + ``input_node.py`` — lazy call
graphs built with ``.bind(...)``, executed either eagerly (every node one
``.remote()`` call) or compiled into per-actor loops over mutable shm
channels (``compiled_dag_node.py:135``; see ``compiled.py``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: a lazily-bound call in the graph."""

    def with_tensor_transport(self, transport: str = "auto") -> "DAGNode":
        """Annotate how this node's output tensors move to consumers
        (reference ``with_tensor_transport``/``with_type_hint``):

        - ``"auto"`` (default): same-actor consumers get the value by
          reference (zero copies); cross-process consumers get shm.
        - ``"device"``: REQUIRE the value to stay on-device — compile
          fails if any consumer lives in another process, because TPU
          has no cross-process device IPC (one process owns a chip;
          the CUDA-IPC/NCCL channel of the reference has no TPU
          analogue — cross-chip movement belongs to XLA collectives
          inside one program, see parallel/).
        - ``"shm"``: always stage through the shm channel.
        """
        if transport not in ("auto", "device", "shm"):
            raise ValueError(f"unknown tensor transport {transport!r}")
        self.transport = transport
        return self

    def execute(self, *args, **kwargs):
        """Classic execution: walk the DAG, one ``.remote()`` per node,
        returning an ObjectRef (or list for MultiOutputNode)."""
        from ray_tpu.dag.compiled import execute_classic

        return execute_classic(self, args, kwargs)

    def experimental_compile(
        self,
        *,
        _buffer_size_bytes: int = 1 << 20,
        _max_inflight_executions: int = 8,
        _timeout_s: float = 30.0,
    ):
        """Compile into per-actor loops over shm channels
        (reference ``dag_node.experimental_compile``)."""
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(
            self,
            buffer_size_bytes=_buffer_size_bytes,
            max_inflight=_max_inflight_executions,
            timeout_s=_timeout_s,
        )

    # traversal
    def _upstream(self) -> List["DAGNode"]:
        return [a for a in getattr(self, "args", ()) if isinstance(a, DAGNode)] + [
            v for v in getattr(self, "kwargs", {}).values() if isinstance(v, DAGNode)
        ]


class InputNode(DAGNode):
    """The driver-provided input. Usable as a context manager
    (``with InputNode() as inp``) for reference parity; attribute/item
    access returns accessor nodes for multi-arg inputs."""

    _local = threading.local()

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def __getattr__(self, name: str) -> "InputAttributeNode":
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name)


class InputAttributeNode(DAGNode):
    """``inp[i]`` / ``inp.key`` — selects one piece of a multi-part input."""

    def __init__(self, parent: InputNode, key):
        self.parent = parent
        self.key = key

    def _upstream(self) -> List[DAGNode]:
        return [self.parent]


class ActorMethodNode(DAGNode):
    """A bound actor method call (``actor.method.bind(...)``)."""

    def __init__(self, handle, method_name: str, args: Tuple, kwargs: Dict, opts: Dict):
        self.handle = handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        self.opts = opts


class FunctionNode(DAGNode):
    """A bound remote function (``fn.bind(...)``) — supported in classic
    execution; compiled graphs require actor methods (loops need a
    process to live in; reference has the same restriction)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs


class ActorClassNode(DAGNode):
    """``Cls.bind(...)`` — a DAG-owned actor, instantiated on first use.
    Only literal constructor args are supported."""

    def __init__(self, actor_cls, args: Tuple, kwargs: Dict):
        self.actor_cls = actor_cls
        self.args = args
        self.kwargs = kwargs
        self._handle = None
        self._lock = threading.Lock()
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, DAGNode):
                raise ValueError(
                    "ActorClassNode constructor args must be literals"
                )

    def get_handle(self):
        with self._lock:
            if self._handle is None:
                self._handle = self.actor_cls.remote(*self.args, **self.kwargs)
            return self._handle

    def __getattr__(self, name: str):
        if name.startswith("_") or name in ("actor_cls", "args", "kwargs", "get_handle"):
            raise AttributeError(name)

        class _BoundMethod:
            def __init__(inner, outer, method):
                inner.outer = outer
                inner.method = method

            def bind(inner, *args, **kwargs):
                handle = inner.outer.get_handle()
                return getattr(handle, inner.method).bind(*args, **kwargs)

        return _BoundMethod(self, name)


class MultiOutputNode(DAGNode):
    """Bundles several terminal nodes; execute/compile return one value
    per output (reference ``ray.dag.MultiOutputNode``)."""

    def __init__(self, outputs: List[DAGNode]):
        self.outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return list(self.outputs)
