"""ray_tpu.data — streaming, block-structured datasets over the runtime.

Reference: ``python/ray/data/`` (Dataset / read_api / streaming executor
/ block batching). See ``dataset.py`` for the TPU-first design notes."""

from ray_tpu.data.block import Block, VALUE_COL
from ray_tpu.data.dataset import Dataset, DataShard
from ray_tpu.data.executor import ActorPoolStrategy
from ray_tpu.data.grouped import (
    AggregateFn,
    Count,
    GroupedData,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from ray_tpu.data.datasink import (
    CSVSink,
    Datasink,
    JSONSink,
    NumpySink,
    ParquetSink,
)
from ray_tpu.data.read_api import (
    Datasource,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range_,
    read_csv,
    read_datasource,
    read_json,
    read_numpy,
    read_parquet,
)

#: reference-parity alias (``ray.data.range``)
range = range_  # noqa: A001

__all__ = [
    "ActorPoolStrategy",
    "AggregateFn",
    "Block",
    "Count",
    "GroupedData",
    "Max",
    "Mean",
    "Min",
    "Std",
    "Sum",
    "VALUE_COL",
    "CSVSink",
    "Datasink",
    "Datasource",
    "Dataset",
    "DataShard",
    "JSONSink",
    "NumpySink",
    "ParquetSink",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_",
    "read_csv",
    "read_datasource",
    "read_json",
    "read_numpy",
    "read_parquet",
]
