"""Blocks: the unit of data movement.

Reference: ``python/ray/data/block.py`` — a block is a batch of rows
stored column-major behind an ObjectRef; operators exchange block refs,
never materialized data, so all movement is zero-copy through the shm
store.

TPU-native delta: the canonical in-memory format is a dict of numpy
arrays (host staging for ``jax.device_put``), not Arrow — Arrow appears
only at the datasource boundary (parquet/csv readers convert)."""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Sequence

import numpy as np

#: A block is a dict of equal-length column arrays.
Block = Dict[str, np.ndarray]

VALUE_COL = "value"  # column name for schemaless datasets (from_items/range)


def normalize_block(data: Any) -> Block:
    """Coerce rows/arrays/dicts into the canonical column-dict block."""
    if isinstance(data, dict):
        return {k: np.asarray(v) for k, v in data.items()}
    arr = np.asarray(data)
    return {VALUE_COL: arr}


def block_num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_slice(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


def block_concat(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if b]  # empty ({}) blocks contribute nothing
    if not blocks:
        return {}
    if len(blocks) == 1:
        return blocks[0]
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def apply_batched(fn, block: Block, batch_size: int) -> Block:
    """Run ``fn`` over ``batch_size``-row slices of a block and concat
    the outputs (shared by Dataset.map_batches and the actor pool)."""
    outs = []
    n = block_num_rows(block)
    for s in range(0, n, batch_size):
        outs.append(normalize_block(fn(block_slice(block, s, min(n, s + batch_size)))))
    return block_concat(outs) if outs else block


def block_take(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def rows_of(block: Block) -> Iterable[Dict[str, Any]]:
    n = block_num_rows(block)
    keys = list(block.keys())
    for i in range(n):
        row = {k: block[k][i] for k in keys}
        yield row[VALUE_COL] if keys == [VALUE_COL] else row


def blocks_from_rows(rows: List[Any], target_block_size: int) -> List[Block]:
    out = []
    for start in range(0, len(rows), target_block_size):
        chunk = rows[start : start + target_block_size]
        if chunk and isinstance(chunk[0], dict):
            keys = chunk[0].keys()
            out.append({k: np.asarray([r[k] for r in chunk]) for k in keys})
        else:
            out.append({VALUE_COL: np.asarray(chunk)})
    return out
