"""Dataset: lazy, streaming, block-structured data over the object store.

Reference surface: ``python/ray/data/dataset.py`` (map_batches / filter /
flat_map / random_shuffle / limit / iter_batches / streaming_split /
count / take / materialize) + ``read_api.py`` (from_items / range /
read_parquet / read_csv / from_numpy / from_pandas).

Design (idiomatic, not a port): a Dataset is (sources, fused transform
chain), where a source is a read callable OR an ObjectRef to an already
materialized block. Transforms append to the chain; execution fuses the
whole chain into ONE remote task per block (reference MapFusion), blocks
stream with bounded in-flight tasks, and consumers pull block refs as
they complete.

``streaming_split(n)`` partitions the *sources* deterministically
(shard i takes sources i, i+n, ...): each shard is an independent
Dataset the consuming worker executes itself. That makes shards
re-iterable (epoch 2 re-executes the plan — reference semantics),
keeps memory bounded by each consumer's in-flight window, and needs no
coordinator. The trade-off vs the reference's splitter actor is static
assignment instead of dynamic balancing of slow consumers."""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    VALUE_COL,
    block_concat,
    block_num_rows,
    block_slice,
    block_take,
    blocks_from_rows,
    normalize_block,
    rows_of,
)
from ray_tpu.data.executor import (
    ActorPoolStrategy,
    ActorStage,
    FusedStage,
    Source,
    execute_pipeline,
)
from ray_tpu.data.iterator import iter_batches_from_refs, iter_device_batches

DEFAULT_BLOCK_SIZE = 1024  # rows per block for in-memory sources


class Dataset:
    """Lazy dataset: construct via ``ray_tpu.data.from_items/range/read_*``.

    The plan is (sources, stages): consecutive map-like transforms fuse
    into one task per block (FusedStage); stateful ``map_batches`` with
    ``compute=ActorPoolStrategy(...)`` breaks fusion into an ActorStage
    (reference: operator fusion rules + ActorPoolMapOperator)."""

    def __init__(self, sources: Sequence[Source], stages=None):
        self._sources: List[Source] = list(sources)
        self._stages: List[Any] = list(stages or [])
        self._materialized: Optional[List[Any]] = None  # block refs cache

    # -- transforms (lazy, fused) ---------------------------------------
    def _plan(self):
        """(sources, stages) this dataset would execute."""
        if self._materialized is not None:
            return list(self._materialized), []
        return self._sources, self._stages

    def _chain(self, t: Callable[[Block], Block]) -> "Dataset":
        sources, stages = self._plan()
        if stages and isinstance(stages[-1], FusedStage):
            stages = stages[:-1] + [stages[-1].chained(t)]
        else:
            stages = stages + [FusedStage([t])]
        return Dataset(sources, stages)

    def map_batches(
        self,
        fn: Any,
        *,
        batch_size: Optional[int] = None,
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[Dict[str, Any]] = None,
    ) -> "Dataset":
        """Apply ``fn`` to whole blocks (optionally re-chunked to
        ``batch_size`` rows inside the task). With
        ``compute=ActorPoolStrategy(...)``, ``fn`` must be a CLASS —
        constructed once per pool actor (expensive state like a loaded
        model amortizes across blocks; reference ActorPoolMapOperator)."""
        if compute is not None:
            if not isinstance(fn, type):
                raise ValueError(
                    "compute=ActorPoolStrategy requires a callable CLASS"
                )
            sources, stages = self._plan()
            return Dataset(
                sources,
                stages
                + [
                    ActorStage(
                        fn, fn_constructor_args, fn_constructor_kwargs or {},
                        compute, batch_size,
                    )
                ],
            )
        if batch_size is None:
            return self._chain(lambda b: normalize_block(fn(b)))
        from ray_tpu.data.block import apply_batched

        return self._chain(lambda b: apply_batched(fn, b, batch_size))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def per_row(block: Block) -> Block:
            rows = [fn(r) for r in rows_of(block)]
            return blocks_from_rows(rows, len(rows) or 1)[0] if rows else block
        return self._chain(per_row)

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        def filt(block: Block) -> Block:
            mask = np.asarray([bool(fn(r)) for r in rows_of(block)], bool)
            return block_take(block, np.nonzero(mask)[0])
        return self._chain(filt)

    def flat_map(self, fn: Callable[[Any], Sequence[Any]]) -> "Dataset":
        def fm(block: Block) -> Block:
            rows: List[Any] = []
            for r in rows_of(block):
                rows.extend(fn(r))
            blocks = blocks_from_rows(rows, max(1, len(rows)))
            return blocks[0] if blocks else {VALUE_COL: np.asarray([])}
        return self._chain(fm)

    # -- execution -------------------------------------------------------
    def _block_refs(self) -> List[Any]:
        if self._materialized is None:
            self._materialized = list(
                execute_pipeline(self._sources, self._stages)
            )
        return self._materialized

    def _stream_refs(self) -> Iterator[Any]:
        if self._materialized is not None:
            return iter(self._materialized)
        return execute_pipeline(self._sources, self._stages)

    def materialize(self) -> "Dataset":
        self._block_refs()
        return self

    # -- global ops (require materialization) ----------------------------
    def random_shuffle(
        self, *, seed: Optional[int] = None, num_blocks: Optional[int] = None
    ) -> "Dataset":
        """Global shuffle as a DISTRIBUTED map/reduce exchange
        (``data/shuffle.py``; reference push-based shuffle,
        ``push_based_shuffle_task_scheduler.py:590``): rows scatter to
        random output partitions in map tasks, reducers merge + permute.
        The driver touches refs only — the data plane stays in the
        object store (spilling under pressure), so a store-oversized
        dataset shuffles without driver materialization."""
        from ray_tpu.data.shuffle import shuffle_exchange

        refs = self._block_refs()
        if not refs:
            return self
        out = shuffle_exchange(refs, num_output_blocks=num_blocks, seed=seed)
        ds = Dataset(out)
        ds._materialized = list(out)  # reducer outputs ARE the blocks
        return ds

    def repartition(self, num_blocks: int) -> "Dataset":
        refs = self._block_refs()
        blocks = [ray_tpu.get(r, timeout=600) for r in refs]
        if not blocks:
            return self
        merged = block_concat(blocks)
        n = block_num_rows(merged)
        per = max(1, -(-n // num_blocks))
        return _from_blocks(
            [block_slice(merged, s, min(n, s + per)) for s in range(0, n, per)]
        )

    def groupby(self, key: str):
        """Group by a column (reference ``Dataset.groupby`` →
        ``GroupedData``): distributed partial-aggregate + hash shuffle."""
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Global sample-sort by a column (reference ``Dataset.sort``)."""
        from ray_tpu.data.grouped import sort_dataset

        return sort_dataset(self, key, descending)

    def unique(self, column: str) -> List[Any]:
        vals = set()
        for ref in self._stream_refs():
            b = ray_tpu.get(ref, timeout=600)
            vals.update(np.unique(b[column]).tolist())
        return sorted(vals)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two equal-length datasets (reference
        ``Dataset.zip``; right-side name collisions get a ``_1``
        suffix)."""
        left = [ray_tpu.get(r, timeout=600) for r in self._block_refs()]
        right = [ray_tpu.get(r, timeout=600) for r in other._block_refs()]
        lm = block_concat(left) if left else {}
        rm = block_concat(right) if right else {}
        ln, rn = block_num_rows(lm), block_num_rows(rm)
        if ln != rn:
            raise ValueError(f"zip() requires equal row counts ({ln} vs {rn})")
        out = dict(lm)
        for k, v in rm.items():
            out[k if k not in out else f"{k}_1"] = v
        per = max(1, ln // max(1, len(left) or 1))
        return _from_blocks(
            [block_slice(out, s, min(ln, s + per)) for s in range(0, ln, per)]
        )

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (reference ``Dataset.union``): each
        side's plan executes independently; blocks chain in order."""
        refs: List[Any] = list(self._block_refs())
        for o in others:
            refs.extend(o._block_refs())
        ds = Dataset(refs)
        ds._materialized = list(refs)
        return ds

    def limit(self, n: int) -> "Dataset":
        taken: List[Block] = []
        have = 0
        for ref in self._stream_refs():
            b = ray_tpu.get(ref, timeout=600)
            rows = block_num_rows(b)
            if have + rows >= n:
                taken.append(block_slice(b, 0, n - have))
                have = n
                break
            taken.append(b)
            have += rows
        return _from_blocks(taken)

    # -- consumption -----------------------------------------------------
    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        prefetch_blocks: int = 2,
    ) -> Iterator[Block]:
        return iter_batches_from_refs(
            self._stream_refs(),
            batch_size=batch_size,
            drop_last=drop_last,
            prefetch_blocks=prefetch_blocks,
        )

    def iter_device_batches(self, *, batch_size=256, sharding=None, transform=None,
                            drop_last: bool = False):
        """Batches double-buffered onto the accelerator (host→device
        overlap) — the TPU ingest path for JaxTrainer."""
        return iter_device_batches(
            self.iter_batches(batch_size=batch_size, drop_last=drop_last),
            sharding=sharding,
            transform=transform,
        )

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_size=None):
            yield from rows_of(batch)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for r in self.iter_rows():
            out.append(r)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(
            block_num_rows(ray_tpu.get(r, timeout=600)) for r in self._stream_refs()
        )

    def schema(self) -> Optional[Dict[str, str]]:
        for ref in self._stream_refs():
            b = ray_tpu.get(ref, timeout=600)
            return {k: str(v.dtype) for k, v in b.items()}
        return None

    def num_blocks(self) -> int:
        if self._materialized is not None:
            return len(self._materialized)
        return len(self._sources)

    # -- splitting -------------------------------------------------------
    def split(self, n: int) -> List["Dataset"]:
        """Materializing equal (by rows) split (reference ``Dataset.split``)."""
        refs = self._block_refs()
        blocks = [ray_tpu.get(r, timeout=600) for r in refs]
        merged = block_concat(blocks) if blocks else {VALUE_COL: np.asarray([])}
        total = block_num_rows(merged)
        per = total // n
        out = []
        for i in range(n):
            end = (i + 1) * per if i < n - 1 else total
            out.append(_from_blocks([block_slice(merged, i * per, end)]))
        return out

    def streaming_split(self, n: int, *, equal: bool = False) -> List["DataShard"]:
        """N disjoint, independently-executing, re-iterable shards — one
        per Train worker (reference ``Dataset.streaming_split``).

        ``equal=True`` materializes and splits by rows exactly;
        ``equal=False`` (default) partitions sources round-robin with no
        materialization (block-granular, so row counts may differ by up
        to one block)."""
        if equal:
            parts = self.split(n)
            return [
                DataShard(p._materialized or p._sources, [], i, n)
                for i, p in enumerate(parts)
            ]
        sources, stages = self._plan()
        return [DataShard(sources[i::n], stages, i, n) for i in range(n)]

    # -- write path ------------------------------------------------------
    def write_datasink(self, sink) -> List[Any]:
        """Write via a ``Datasink`` (reference ``datasink.py:51``): one
        remote task per block; driver handles lifecycle hooks only."""
        from ray_tpu.data.datasink import write_datasink

        return write_datasink(self, sink)

    def write_parquet(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import ParquetSink

        return self.write_datasink(ParquetSink(path))

    def write_csv(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import CSVSink

        return self.write_datasink(CSVSink(path))

    def write_json(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import JSONSink

        return self.write_datasink(JSONSink(path))

    def write_numpy(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import NumpySink

        return self.write_datasink(NumpySink(path))

    def __repr__(self) -> str:
        return (
            f"Dataset(blocks={self.num_blocks()}, "
            f"stages={len(self._stages)})"
        )


def _from_blocks(blocks: List[Block]) -> Dataset:
    refs = [ray_tpu.put(b) for b in blocks]
    ds = Dataset(refs)
    ds._materialized = list(refs)
    return ds


class DataShard(Dataset):
    """One consumer's shard of a streaming_split — picklable (sources are
    read callables or ObjectRefs), re-iterable every epoch, executed by
    whichever worker consumes it."""

    def __init__(self, sources, stages, split_idx: int, num_splits: int):
        super().__init__(sources, stages)
        self._idx = split_idx
        self._n = num_splits

    def __reduce__(self):
        return (
            DataShard,
            (self._sources, self._stages, self._idx, self._n),
        )

    def __repr__(self) -> str:
        return f"DataShard({self._idx}/{self._n}, blocks={self.num_blocks()})"
