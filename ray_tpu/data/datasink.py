"""Write path: Datasink ABC + file-format sinks.

Reference: ``python/ray/data/datasource/datasink.py:51`` (``Datasink``
with ``on_write_start`` / ``write`` / ``on_write_complete`` /
``on_write_failed``) and the per-format sinks under
``_internal/datasource/``. Writes are one REMOTE TASK per block — the
driver moves refs only; each task writes its own ``part-{i:06d}.{ext}``
file (the reference's filename-provider convention), so a
store-oversized dataset streams to disk without driver materialization.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, VALUE_COL, block_num_rows


class Datasink:
    """Subclass and implement ``write`` (called once per block, inside a
    remote task). Driver-side lifecycle hooks run around the job."""

    def on_write_start(self) -> None:  # driver, before any task
        pass

    def write(self, block: Block, ctx: Dict[str, Any]) -> Any:
        """Write one block. ``ctx`` carries ``task_index``. The return
        value is collected into ``on_write_complete(results)``."""
        raise NotImplementedError

    def on_write_complete(self, results: List[Any]) -> None:  # driver
        pass

    def on_write_failed(self, error: Exception) -> None:  # driver
        pass


def _write_block_task(sink: Datasink, block: Block, task_index: int):
    return sink.write(block, {"task_index": task_index})


_write_remote = None


def write_datasink(dataset, sink: Datasink) -> List[Any]:
    """Drive a write job: one task per block, lifecycle hooks around it
    (reference ``Dataset.write_datasink``)."""
    global _write_remote
    if _write_remote is None:
        _write_remote = ray_tpu.remote(num_cpus=1)(_write_block_task)
    sink.on_write_start()
    try:
        refs = [
            _write_remote.remote(sink, ref, i)
            for i, ref in enumerate(dataset._stream_refs())
        ]
        results = ray_tpu.get(refs, timeout=600)
        sink.on_write_complete(results)
    except Exception as e:  # noqa: BLE001
        # completion failures route through on_write_failed too — the
        # sink must get a chance to clean staged output either way
        sink.on_write_failed(e)
        raise
    return results


# ---------------------------------------------------------------------------
# file-format sinks


class _FileSink(Datasink):
    ext = "bin"

    def __init__(self, path: str):
        self.path = path

    def on_write_start(self) -> None:
        os.makedirs(self.path, exist_ok=True)

    def _filename(self, ctx) -> str:
        return os.path.join(self.path, f"part-{ctx['task_index']:06d}.{self.ext}")


class ParquetSink(_FileSink):
    ext = "parquet"

    def write(self, block: Block, ctx) -> str:
        import pyarrow as pa
        import pyarrow.parquet as pq

        table = pa.table({k: np.asarray(v) for k, v in block.items()})
        out = self._filename(ctx)
        pq.write_table(table, out)
        return out


class CSVSink(_FileSink):
    ext = "csv"

    def write(self, block: Block, ctx) -> str:
        import csv

        out = self._filename(ctx)
        keys = list(block.keys())
        with open(out, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(keys)
            n = block_num_rows(block)
            for i in range(n):
                w.writerow([block[k][i] for k in keys])
        return out


class JSONSink(_FileSink):
    """JSON-lines (one object per row — the reference's default)."""

    ext = "json"

    def write(self, block: Block, ctx) -> str:
        import json

        out = self._filename(ctx)
        keys = list(block.keys())
        with open(out, "w") as f:
            n = block_num_rows(block)
            for i in range(n):
                row = {k: _jsonable(block[k][i]) for k in keys}
                if keys == [VALUE_COL]:
                    row = row[VALUE_COL]
                f.write(json.dumps(row) + "\n")
        return out


class NumpySink(_FileSink):
    ext = "npz"

    def write(self, block: Block, ctx) -> str:
        out = self._filename(ctx)
        np.savez(out.rsplit(".", 1)[0], **{k: np.asarray(v) for k, v in block.items()})
        return out


def _jsonable(v: Any):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v
