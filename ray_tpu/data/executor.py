"""Streaming execution over the task pool.

Reference: ``data/_internal/execution/streaming_executor.py:48,89`` +
``operators/task_pool_map_operator.py`` — blocks stream through remote
tasks with bounded in-flight work (backpressure against the object
store), and consecutive map stages are FUSED into one task per block
(the reference's MapFusion rewrite) so intermediate blocks never exist.

A *source* is either a no-arg read callable (fresh execution) or an
ObjectRef to an existing block (re-transforming materialized data): ref
sources are passed as task *arguments* so the dependency protocol
fetches them on the executing worker.

The executor yields block ObjectRefs as they become ready — consumption
(iter_batches / streaming_split) overlaps with production."""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Sequence, Union

import ray_tpu
from ray_tpu.data.block import Block, normalize_block

#: a transform maps one block to one block (fused chains compose)
Transform = Callable[[Block], Block]
#: read callable or a block ref
Source = Union[Callable[[], Any], "ray_tpu.ObjectRef"]


def _fused_task(read_fn, block, transforms: Sequence[Transform]) -> Block:
    out = normalize_block(block if read_fn is None else read_fn())
    for t in transforms:
        out = normalize_block(t(out))
    return out


_fused_remote = None


def _get_remote():
    global _fused_remote
    if _fused_remote is None:
        _fused_remote = ray_tpu.remote(num_cpus=1)(_fused_task)
    return _fused_remote


def _submit(source: Source, transforms: Sequence[Transform]):
    remote_fn = _get_remote()
    if isinstance(source, ray_tpu.ObjectRef):
        # ref source: ship as an arg so the dep protocol fetches the block
        return remote_fn.remote(None, source, list(transforms))
    return remote_fn.remote(source, None, list(transforms))


def execute_streaming(
    sources: Sequence[Source],
    transforms: Sequence[Transform],
    *,
    max_inflight: int = 8,
) -> Iterator["ray_tpu.ObjectRef"]:
    """Run ``transforms`` fused over every source; yield block refs in
    SOURCE order (reference ray.data preserves block order, so take()/
    limit() are deterministic) with at most ``max_inflight`` tasks
    outstanding. Later tasks keep running while the head block is
    awaited — order costs no pipeline parallelism, only yield order."""
    if not transforms and sources and all(
        isinstance(s, ray_tpu.ObjectRef) for s in sources
    ):
        # materialized + no work: the blocks ARE the result
        yield from sources
        return
    pending: List[Any] = []
    idx = 0
    n = len(sources)
    while idx < n or pending:
        while idx < n and len(pending) < max_inflight:
            pending.append(_submit(sources[idx], transforms))
            idx += 1
        head = pending.pop(0)
        ray_tpu.wait([head], num_returns=1, timeout=None, fetch_local=False)
        yield head


def execute_all(
    sources: Sequence[Source],
    transforms: Sequence[Transform],
    *,
    max_inflight: int = 8,
) -> List["ray_tpu.ObjectRef"]:
    return list(execute_streaming(sources, transforms, max_inflight=max_inflight))
