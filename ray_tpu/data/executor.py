"""Streaming execution over the task pool and actor pools.

Reference: ``data/_internal/execution/streaming_executor.py:48,89`` +
``operators/task_pool_map_operator.py`` + ``actor_pool_map_operator.py``
+ ``backpressure_policy/`` — blocks stream through remote tasks with
bounded in-flight work, consecutive map stages FUSE into one task per
block (MapFusion), stateful stages run on an autoscaling actor pool, and
admission control is keyed to OBJECT STORE USAGE (not a constant): the
driver polls the node daemon's store stats and pauses submission while
the store sits above the spill threshold, so a 10x-oversized dataset
streams through a capacity-limited store instead of flooding it.

A *source* is either a no-arg read callable (fresh execution) or an
ObjectRef to an existing block (re-transforming materialized data): ref
sources are passed as task *arguments* so the dependency protocol
fetches them on the executing worker.

The executor yields block ObjectRefs as they become ready — consumption
(iter_batches / streaming_split) overlaps with production."""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator, List, Optional, Sequence, Union

import ray_tpu
from ray_tpu.data.block import Block, normalize_block

#: a transform maps one block to one block (fused chains compose)
Transform = Callable[[Block], Block]
#: read callable or a block ref
Source = Union[Callable[[], Any], "ray_tpu.ObjectRef"]


# ---------------------------------------------------------------------------
# stages


class FusedStage:
    """A chain of block→block transforms executed as ONE task per block."""

    def __init__(self, transforms: Optional[List[Transform]] = None):
        self.transforms: List[Transform] = list(transforms or [])

    def chained(self, t: Transform) -> "FusedStage":
        return FusedStage(self.transforms + [t])


class ActorStage:
    """A stateful map stage on an autoscaling actor pool (reference
    ``ActorPoolMapOperator``): ``cls`` is constructed once per pool actor
    and its ``__call__`` maps blocks."""

    def __init__(self, cls, cls_args, cls_kwargs, strategy, batch_size=None):
        self.cls = cls
        self.cls_args = cls_args or ()
        self.cls_kwargs = cls_kwargs or {}
        self.strategy = strategy
        self.batch_size = batch_size


class ActorPoolStrategy:
    """``compute=`` argument for stateful ``map_batches`` (reference
    ``ray.data.ActorPoolStrategy``)."""

    def __init__(self, size: Optional[int] = None, *, min_size: int = 1, max_size: Optional[int] = None):
        if size is not None:
            min_size = max_size = size
        self.min_size = max(1, min_size)
        self.max_size = max_size or max(self.min_size, 4)


# ---------------------------------------------------------------------------
# fused-task submission


def _fused_task(read_fn, block, transforms: Sequence[Transform]) -> Block:
    out = normalize_block(block if read_fn is None else read_fn())
    for t in transforms:
        out = normalize_block(t(out))
    return out


_fused_remote = None


def _get_remote():
    global _fused_remote
    if _fused_remote is None:
        _fused_remote = ray_tpu.remote(num_cpus=1)(_fused_task)
    return _fused_remote


def _submit(source: Source, transforms: Sequence[Transform]):
    remote_fn = _get_remote()
    if isinstance(source, ray_tpu.ObjectRef):
        # ref source: ship as an arg so the dep protocol fetches the block
        return remote_fn.remote(None, source, list(transforms))
    return remote_fn.remote(source, None, list(transforms))


# ---------------------------------------------------------------------------
# backpressure: admission keyed to store usage


class StoreBackpressure:
    """Pause submissions while the shared object store sits above its
    spill threshold (reference ``backpressure_policy/``): the store stats
    come from the node daemon and are cached briefly. Always admits at
    least one in-flight task so the pipeline cannot deadlock."""

    def __init__(self, poll_period_s: float = 0.25, fraction: float = None):
        from ray_tpu.core.config import GLOBAL_CONFIG

        self._period = poll_period_s
        self._fraction = (
            fraction
            if fraction is not None
            else GLOBAL_CONFIG.object_spilling_threshold
        )
        self._last_poll = 0.0
        self._full = False

    def store_full(self) -> bool:
        now = time.monotonic()
        if now - self._last_poll >= self._period:
            self._last_poll = now
            self._full = self._query()
        return self._full

    def _query(self) -> bool:
        try:
            from ray_tpu.core.api import get_global_worker_or_none

            w = get_global_worker_or_none()
            core = getattr(w, "backend", None) if w else None
            daemon = getattr(core, "daemon", None)
            io = getattr(core, "io", None)
            if daemon is None or io is None:
                return False
            stats = io.run(daemon.call("stats", timeout=5), timeout=6)["store"]
            cap = stats.get("capacity_bytes") or 1
            return (stats.get("used_bytes", 0) / cap) >= self._fraction
        except Exception:
            return False  # stats unavailable: fall back to inflight cap


# ---------------------------------------------------------------------------
# streaming drivers


def execute_streaming(
    sources: Sequence[Source],
    transforms: Sequence[Transform],
    *,
    max_inflight: int = 8,
) -> Iterator["ray_tpu.ObjectRef"]:
    """Run ``transforms`` fused over every source; yield block refs in
    SOURCE order (reference ray.data preserves block order, so take()/
    limit() are deterministic) with at most ``max_inflight`` tasks
    outstanding AND submission paused while the store is over threshold.
    Later tasks keep running while the head block is awaited — order
    costs no pipeline parallelism, only yield order."""
    if not transforms and sources and all(
        isinstance(s, ray_tpu.ObjectRef) for s in sources
    ):
        # materialized + no work: the blocks ARE the result
        yield from sources
        return
    bp = StoreBackpressure()
    pending: List[Any] = []
    idx = 0
    n = len(sources)
    while idx < n or pending:
        while idx < n and len(pending) < max_inflight:
            if pending and bp.store_full():
                break  # let the consumer drain before admitting more
            pending.append(_submit(sources[idx], transforms))
            idx += 1
        head = pending.pop(0)
        ray_tpu.wait([head], num_returns=1, timeout=None, fetch_local=False)
        yield head


def execute_all(
    sources: Sequence[Source],
    transforms: Sequence[Transform],
    *,
    max_inflight: int = 8,
) -> List["ray_tpu.ObjectRef"]:
    return list(execute_streaming(sources, transforms, max_inflight=max_inflight))


# ---------------------------------------------------------------------------
# actor-pool stage driver


class _PoolActorWrapper:
    """Worker-side wrapper: constructs the user's callable class once,
    then maps blocks (optionally re-chunked) through it."""

    def __init__(self, cls, args, kwargs, batch_size):
        self._fn = cls(*args, **kwargs)
        self._batch_size = batch_size

    def apply(self, block: Block) -> Block:
        from ray_tpu.data.block import apply_batched

        if self._batch_size is None:
            return normalize_block(self._fn(block))
        return apply_batched(self._fn, block, self._batch_size)


def _ref_death_error(ref) -> Optional[Exception]:
    """Owner-side peek: the worker/actor-death error a ref resolved to,
    or None. No data fetch — the driver owns stage refs, so failure
    state is local (ownership table)."""
    try:
        from ray_tpu.core.api import get_global_worker_or_none
        from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError
        from ray_tpu.core.ownership import ObjState

        w = get_global_worker_or_none()
        rc = getattr(getattr(w, "backend", None), "refcounter", None)
        if rc is None or not rc.owns(ref.id()):
            return None
        obj = rc.get(ref.id())
        if obj is not None and obj.state == ObjState.FAILED and isinstance(
            obj.error, (ActorDiedError, WorkerCrashedError)
        ):
            return obj.error
    except Exception:
        return None
    return None


def execute_actor_stage(
    upstream: Iterator["ray_tpu.ObjectRef"],
    stage: ActorStage,
    *,
    per_actor_inflight: int = 2,
    max_block_retries: int = 3,
) -> Iterator["ray_tpu.ObjectRef"]:
    """Stream upstream blocks through an autoscaling pool of stateful
    actors. The pool starts at ``min_size`` and grows (up to
    ``max_size``) whenever every actor is saturated and more input is
    waiting; actors die with their handles when the stage completes.

    Fault tolerance: a pool actor dying mid-block (preempted node, OOM
    kill) fails every ref in flight on it — each such block is
    resubmitted to a surviving (or freshly spawned) pool actor, up to
    ``max_block_retries`` attempts per block, instead of failing the
    stage. The input block ref is retained until its result is emitted,
    so the retry re-reads the same upstream data."""
    strategy: ActorPoolStrategy = stage.strategy
    remote_cls = ray_tpu.remote(num_cpus=1)(_PoolActorWrapper)

    def spawn():
        return remote_cls.remote(
            stage.cls, tuple(stage.cls_args), dict(stage.cls_kwargs), stage.batch_size
        )

    pool = [spawn() for _ in range(strategy.min_size)]
    inflight: List[List[Any]] = [[] for _ in pool]  # per-actor pending refs
    out_order: List[Any] = []  # result refs in submission order
    #: result ref -> (input block ref, pool index, attempts so far)
    ref_meta: dict = {}
    bp = StoreBackpressure()

    def least_loaded() -> int:
        return min(range(len(pool)), key=lambda i: len(inflight[i]))

    def reap_done() -> None:
        for lst in inflight:
            while lst and ray_tpu.wait([lst[0]], num_returns=1, timeout=0)[0]:
                lst.pop(0)

    def submit(block_ref, attempts: int = 0):
        i = least_loaded()
        ref = pool[i].apply.remote(block_ref)
        inflight[i].append(ref)
        ref_meta[ref] = (block_ref, pool[i], attempts)
        return ref

    def recover(ref):
        """Resubmit a death-failed result elsewhere; replace the corpse
        in place (once — later failed refs from the same actor find it
        already gone from the pool and simply resubmit)."""
        block_ref, dead, attempts = ref_meta.pop(ref)
        if dead in pool:
            i = pool.index(dead)
            pool[i] = spawn()
            inflight[i] = []
        return submit(block_ref, attempts + 1)

    upstream_iter = iter(upstream)
    exhausted = False
    emitted = 0
    while True:
        # admit while there is capacity (and the store isn't full);
        # backpressure keys on UNCONSUMED work — with nothing in flight
        # and nothing to yield, admission must proceed or the loop would
        # busy-spin forever against a full store
        while not exhausted:
            reap_done()
            i = least_loaded()
            if len(inflight[i]) >= per_actor_inflight:
                if len(pool) < strategy.max_size:
                    pool.append(spawn())
                    inflight.append([])
                    continue
                break
            if len(out_order) > emitted and bp.store_full():
                break
            try:
                block_ref = next(upstream_iter)
            except StopIteration:
                exhausted = True
                break
            out_order.append(submit(block_ref))
        if emitted < len(out_order):
            head = out_order[emitted]
            while True:
                ray_tpu.wait([head], num_returns=1, timeout=None, fetch_local=False)
                err = _ref_death_error(head)
                if err is None:
                    break
                _b, _a, attempts = ref_meta.get(head, (None, None, max_block_retries))
                if attempts >= max_block_retries:
                    break  # exhausted: the failure propagates to the consumer
                head = recover(head)
            ref_meta.pop(head, None)
            out_order[emitted] = None  # don't pin emitted blocks for the stage lifetime
            emitted += 1
            yield head
            continue
        if exhausted:
            break
    # pool handles drop here → actors terminate gracefully (handle GC)


def execute_pipeline(
    sources: Sequence[Source],
    stages: Sequence[Any],
    *,
    max_inflight: int = 8,
) -> Iterator["ray_tpu.ObjectRef"]:
    """Compose the stage list into one streaming iterator: consecutive
    FusedStages were already merged by the Dataset; ActorStages stream
    between them."""
    stream: Optional[Iterator[Any]] = None
    first = True
    for stage in stages:
        if isinstance(stage, FusedStage):
            if first:
                stream = execute_streaming(
                    sources, stage.transforms, max_inflight=max_inflight
                )
            else:
                stream = _refs_through_tasks(stream, stage.transforms, max_inflight)
        elif isinstance(stage, ActorStage):
            if first:
                stream = execute_streaming(sources, [], max_inflight=max_inflight)
            stream = execute_actor_stage(stream, stage)
        else:
            raise TypeError(f"unknown stage {stage!r}")
        first = False
    if stream is None:
        stream = execute_streaming(sources, [], max_inflight=max_inflight)
    return stream


def _refs_through_tasks(
    upstream: Iterator["ray_tpu.ObjectRef"],
    transforms: Sequence[Transform],
    max_inflight: int,
) -> Iterator["ray_tpu.ObjectRef"]:
    """Fused transforms applied to an upstream ref stream."""
    if not transforms:
        yield from upstream
        return
    bp = StoreBackpressure()
    pending: List[Any] = []
    upstream_iter = iter(upstream)
    exhausted = False
    while not exhausted or pending:
        while not exhausted and len(pending) < max_inflight:
            if pending and bp.store_full():
                break
            try:
                src = next(upstream_iter)
            except StopIteration:
                exhausted = True
                break
            pending.append(_submit(src, transforms))
        if not pending:
            continue
        head = pending.pop(0)
        ray_tpu.wait([head], num_returns=1, timeout=None, fetch_local=False)
        yield head
