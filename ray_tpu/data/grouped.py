"""Grouped aggregation and sorting.

Reference: ``python/ray/data/grouped_data.py`` (GroupedData.count/sum/
min/max/mean/aggregate/map_groups), ``data/aggregate.py`` (AggregateFn),
and the sort exchange (``_internal/planner/exchange/sort_task_spec.py``).

Execution is a two-stage task shuffle, not a driver-side pandas pass:
map tasks partial-aggregate each block and hash-partition the partial
states by key; reduce tasks merge their partition across all map outputs
and finalize. Sort samples key boundaries, range-partitions blocks in map
tasks, and sorts each range in reduce tasks — output blocks are globally
ordered. (The reference's push-based shuffle pipelines the exchange; this
build ships whole map outputs, the honest small-scale equivalent.)
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


def _sorted_group_segments(block: Block, key: str):
    """Stable-sort a block by ``key`` and return
    ``(sorted_block, sorted_keys, starts, ends)`` where each
    ``[starts[i], ends[i])`` is one group's contiguous segment — the one
    grouping idiom shared by map, partition, and reduce tasks."""
    order = np.argsort(block[key], kind="stable")
    sb = block_take(block, order)
    sk = sb[key]
    bounds = np.flatnonzero(sk[1:] != sk[:-1]) + 1
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [len(sk)]])
    return sb, sk, starts, ends


def _det_hash(value: Any) -> int:
    """Deterministic cross-process key hash: Python's ``hash()`` is
    salted per process (PYTHONHASHSEED), which would route the same key
    to DIFFERENT partitions in different map workers — silent groupby
    corruption."""
    return int.from_bytes(
        hashlib.blake2b(repr(value).encode(), digest_size=8).digest(), "little"
    )

import ray_tpu
from ray_tpu.data.block import (
    Block,
    block_concat,
    block_num_rows,
    block_take,
    normalize_block,
)


class AggregateFn:
    """One aggregation (reference ``ray.data.aggregate.AggregateFn``):
    ``init(key)->state``, ``accumulate_block(state, block)->state``,
    ``merge(a, b)->state``, ``finalize(state)->value``."""

    def __init__(self, init, accumulate_block, merge, finalize=None, name="agg()"):
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize or (lambda s: s)
        self.name = name


def Count() -> AggregateFn:
    return AggregateFn(
        init=lambda k: 0,
        accumulate_block=lambda s, b: s + block_num_rows(b),
        merge=lambda a, b: a + b,
        name="count()",
    )


def _col_agg(on: str, np_fn, np_merge, name: str) -> AggregateFn:
    return AggregateFn(
        init=lambda k: None,
        accumulate_block=lambda s, b: (
            np_fn(b[on]) if s is None else np_merge(s, np_fn(b[on]))
        ),
        merge=lambda a, b: b if a is None else (a if b is None else np_merge(a, b)),
        name=f"{name}({on})",
    )


def Sum(on: str) -> AggregateFn:
    return _col_agg(on, np.sum, lambda a, b: a + b, "sum")


def Min(on: str) -> AggregateFn:
    return _col_agg(on, np.min, min, "min")


def Max(on: str) -> AggregateFn:
    return _col_agg(on, np.max, max, "max")


def Mean(on: str) -> AggregateFn:
    return AggregateFn(
        init=lambda k: (0.0, 0),
        accumulate_block=lambda s, b: (s[0] + float(np.sum(b[on])), s[1] + len(b[on])),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda s: s[0] / s[1] if s[1] else float("nan"),
        name=f"mean({on})",
    )


def Std(on: str, ddof: int = 1) -> AggregateFn:
    # parallel variance via (n, sum, sumsq); ddof=1 (sample std) matches
    # the reference ray.data.aggregate.Std default
    def _finalize(s):
        n = s[0]
        if n <= ddof:
            return float("nan")
        var = (s[2] - s[1] * s[1] / n) / (n - ddof)
        return float(np.sqrt(max(0.0, var)))

    return AggregateFn(
        init=lambda k: (0, 0.0, 0.0),
        accumulate_block=lambda s, b: (
            s[0] + len(b[on]),
            s[1] + float(np.sum(b[on])),
            s[2] + float(np.sum(np.square(b[on].astype(np.float64)))),
        ),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
        finalize=_finalize,
        name=f"std({on})",
    )


# ---------------------------------------------------------------------------
# shuffle tasks (run remotely)


def _group_map_task(block: Block, key: str, aggs: List[AggregateFn], num_parts: int):
    """Partial-aggregate one block; hash-partition states by key.
    Returns [ {key_value: [state_per_agg]} ] * num_parts."""
    block = normalize_block(block)
    parts: List[Dict[Any, List[Any]]] = [{} for _ in range(num_parts)]
    keys = block[key]
    if len(keys) == 0:
        return parts
    sorted_block, skeys, starts, ends = _sorted_group_segments(block, key)
    for s, e in zip(starts, ends):
        kv = skeys[s]
        sub = {c: v[s:e] for c, v in sorted_block.items()}
        kv_py = kv.item() if hasattr(kv, "item") else kv
        part = parts[_det_hash(kv_py) % num_parts]
        states = part.get(kv_py)
        if states is None:
            states = part[kv_py] = [a.init(kv_py) for a in aggs]
        for i, a in enumerate(aggs):
            states[i] = a.accumulate_block(states[i], sub)
    return parts


def _group_reduce_task(part_idx: int, key: str, aggs: List[AggregateFn], *map_outputs):
    """Merge one hash partition across every map output; finalize."""
    merged: Dict[Any, List[Any]] = {}
    for mo in map_outputs:
        for kv, states in mo[part_idx].items():
            cur = merged.get(kv)
            if cur is None:
                merged[kv] = list(states)
            else:
                for i, a in enumerate(aggs):
                    cur[i] = a.merge(cur[i], states[i])
    if not merged:
        return {}
    kvs = sorted(merged.keys())
    out: Dict[str, Any] = {key: np.asarray(kvs)}
    for i, a in enumerate(aggs):
        out[a.name] = np.asarray([a.finalize(merged[kv][i]) for kv in kvs])
    return out


def _group_rows_partition_task(block: Block, key: str, num_parts: int):
    """Hash-partition one block's raw rows by key. num_parts RETURN
    VALUES (one ObjectRef per partition), so each reducer fetches only
    its own partition instead of every map output."""
    b = normalize_block(block)
    keys = b[key]
    if len(keys) == 0:
        empty = [{} for _ in range(num_parts)]
        return empty if num_parts > 1 else empty[0]
    # one hash per GROUP, not per row: sort once, find group boundaries,
    # assign each segment its partition (same technique as the reduce)
    sb, sk, starts, ends = _sorted_group_segments(b, key)
    part_of = np.empty(len(sk), dtype=np.int64)
    for s, e in zip(starts, ends):
        kv = sk[s]
        part_of[s:e] = _det_hash(kv.item() if hasattr(kv, "item") else kv) % num_parts
    parts = [block_take(sb, np.nonzero(part_of == p)[0]) for p in range(num_parts)]
    return parts if num_parts > 1 else parts[0]


def _map_groups_reduce_task(key: str, fn, *part_blocks):
    """Concat this partition's rows across blocks, group by key, apply
    ``fn`` per group."""
    merged = block_concat([normalize_block(p) for p in part_blocks if p])
    if not merged or len(merged.get(key, ())) == 0:
        return {}
    sb, _sk, starts, ends = _sorted_group_segments(merged, key)
    outs = []
    for s, e in zip(starts, ends):
        outs.append(normalize_block(fn({c: v[s:e] for c, v in sb.items()})))
    return block_concat(outs)


class GroupedData:
    """``ds.groupby(key)`` (reference ``GroupedData``)."""

    def __init__(self, dataset, key: str, num_partitions: Optional[int] = None):
        self._ds = dataset
        self._key = key
        self._parts = num_partitions

    def _num_parts(self, n_blocks: int) -> int:
        return self._parts or max(1, min(8, n_blocks))

    def aggregate(self, *aggs: AggregateFn):
        from ray_tpu.data.dataset import Dataset

        refs = self._ds._block_refs()
        if not refs:
            return Dataset([])
        R = self._num_parts(len(refs))
        map_remote = ray_tpu.remote(num_cpus=1)(_group_map_task)
        red_remote = ray_tpu.remote(num_cpus=1)(_group_reduce_task)
        map_out = [map_remote.remote(r, self._key, list(aggs), R) for r in refs]
        red_out = [
            red_remote.remote(i, self._key, list(aggs), *map_out) for i in range(R)
        ]
        # empty ({}) partitions ride along — block_concat/rows_of skip
        # them, so no driver-side fetch is needed to filter
        ds = Dataset(red_out)
        ds._materialized = list(red_out)
        return ds

    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str):
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable[[Block], Any]):
        """Apply ``fn`` to each group's full block (reference
        ``GroupedData.map_groups``)."""
        from ray_tpu.data.dataset import Dataset

        refs = self._ds._block_refs()
        if not refs:
            return Dataset([])
        R = self._num_parts(len(refs))
        part_remote = ray_tpu.remote(num_cpus=1)(_group_rows_partition_task).options(
            num_returns=R
        )
        mg_remote = ray_tpu.remote(num_cpus=1)(_map_groups_reduce_task)
        cols = [part_remote.remote(r, self._key, R) for r in refs]
        outs = [
            mg_remote.remote(
                self._key, fn, *[(c[i] if R > 1 else c) for c in cols]
            )
            for i in range(R)
        ]
        ds = Dataset(outs)
        ds._materialized = list(outs)
        return ds


# ---------------------------------------------------------------------------
# sort


def _sample_keys_task(block: Block, key: str, k: int) -> List[Any]:
    keys = normalize_block(block)[key]
    if len(keys) == 0:
        return []
    step = max(1, len(keys) // k)
    return np.asarray(keys)[::step].tolist()


def _sort_partition_task(block: Block, key: str, bounds: List[Any], descending: bool):
    """Range-partition one block by the sampled boundaries. One RETURN
    VALUE per partition so each merge task fetches only its range."""
    block = normalize_block(block)
    keys = block[key]
    idx = np.searchsorted(np.asarray(bounds), keys, side="right")
    parts = []
    for p in range(len(bounds) + 1):
        parts.append(block_take(block, np.nonzero(idx == p)[0]))
    if descending:
        parts = parts[::-1]
    return parts if len(parts) > 1 else parts[0]


def _sort_merge_task(key: str, descending: bool, *parts):
    blocks = [b for b in parts if block_num_rows(b) > 0]
    if not blocks:
        return {}
    merged = block_concat(blocks)
    order = np.argsort(merged[key], kind="stable")
    if descending:
        order = order[::-1]
    return block_take(merged, order)


def sort_dataset(ds, key: str, descending: bool = False):
    """Sample-sort (reference sort exchange): sample boundaries → range
    partition (map tasks) → per-range merge-sort (reduce tasks)."""
    from ray_tpu.data.dataset import Dataset

    refs = ds._block_refs()
    if not refs:
        return Dataset([])
    R = max(1, min(8, len(refs)))
    # boundary sampling via remote tasks — full blocks never funnel
    # through the driver (reference SortTaskSpec.sample_boundaries)
    sample_remote = ray_tpu.remote(num_cpus=1)(_sample_keys_task)
    sample_refs = [sample_remote.remote(r, key, 32) for r in refs]
    samples: List[Any] = []
    for sr in sample_refs:
        samples.extend(ray_tpu.get(sr, timeout=600))
    if not samples:
        return Dataset(list(refs))
    samples.sort()
    bounds = [
        samples[int(len(samples) * (i + 1) / R)]
        for i in range(R - 1)
        if int(len(samples) * (i + 1) / R) < len(samples)
    ]
    P = len(bounds) + 1
    part_remote = ray_tpu.remote(num_cpus=1)(_sort_partition_task).options(
        num_returns=P
    )
    merge_remote = ray_tpu.remote(num_cpus=1)(_sort_merge_task)
    cols = [part_remote.remote(r, key, bounds, descending) for r in refs]
    merged = [
        merge_remote.remote(
            key, descending, *[(c[i] if P > 1 else c) for c in cols]
        )
        for i in range(P)
    ]
    out = Dataset(merged)
    out._materialized = list(merged)
    return out
