"""Batch iteration with prefetch + host→device double-buffering.

Reference: ``data/_internal/block_batching/`` (prefetching batchers) and
``data/iterator.py`` — the piece Train actually needs on TPU: while step
N computes on device, batch N+1 is already being sliced on host and
transferred, so input never serializes behind compute."""

from __future__ import annotations

import threading
from queue import Queue
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import ray_tpu
from ray_tpu.data.block import Block, block_concat, block_num_rows, block_slice

_SENTINEL = object()


def iter_batches_from_refs(
    ref_iter: Iterable,
    *,
    batch_size: Optional[int],
    drop_last: bool = False,
    prefetch_blocks: int = 2,
) -> Iterator[Block]:
    """Slice/merge a stream of block refs into batches of ``batch_size``
    rows, fetching up to ``prefetch_blocks`` blocks ahead in a background
    thread (pipeline fill while the consumer computes).

    Abandoning the generator early (take(), a training loop that breaks)
    stops the producer thread: it checks a stop flag around the bounded
    queue put, so it never blocks forever holding blocks alive."""
    q: Queue = Queue(maxsize=max(1, prefetch_blocks))
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except Exception:  # queue.Full
                continue
        return False

    def _producer():
        try:
            for ref in ref_iter:
                if not _put(ray_tpu.get(ref, timeout=600)):
                    return
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — surface in consumer
            _put(e)
            return
        _put(_SENTINEL)

    t = threading.Thread(target=_producer, daemon=True, name="batch-prefetch")
    t.start()

    try:
        leftover: Optional[Block] = None
        while True:
            item = q.get()
            if isinstance(item, BaseException):
                raise item
            if item is _SENTINEL:
                break
            block: Block = item
            if batch_size is None:
                yield block
                continue
            if leftover is not None:
                block = block_concat([leftover, block])
                leftover = None
            n = block_num_rows(block)
            start = 0
            while n - start >= batch_size:
                yield block_slice(block, start, start + batch_size)
                start += batch_size
            if start < n:
                leftover = block_slice(block, start, n)
        if leftover is not None and not drop_last:
            yield leftover
    finally:
        stop.set()
        # drain so a producer blocked mid-put can observe the flag
        try:
            while True:
                q.get_nowait()
        except Exception:
            pass


def iter_device_batches(
    batch_iter: Iterable[Block],
    *,
    sharding=None,
    transform: Optional[Callable[[Block], Dict[str, Any]]] = None,
) -> Iterator[Dict[str, Any]]:
    """Double-buffer host batches onto device: batch N+1's device_put is
    issued (async) while the caller computes on batch N."""
    import jax

    def put(b: Block):
        if transform is not None:
            b = transform(b)
        if sharding is not None:
            return jax.device_put(b, sharding)
        return jax.device_put(b)

    it = iter(batch_iter)
    try:
        current = put(next(it))
    except StopIteration:
        return
    for nxt in it:
        staged = put(nxt)  # async dispatch: overlaps consumer compute
        yield current
        current = staged
    yield current
