"""Dataset creation (reference ``python/ray/data/read_api.py``).

Sources become *read tasks* — no-arg callables, one per block, executed
remotely with the transform chain fused in. File readers use pyarrow at
the boundary and convert to the canonical numpy column-dict block."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ray_tpu.data.block import VALUE_COL, blocks_from_rows, normalize_block
from ray_tpu.data.dataset import DEFAULT_BLOCK_SIZE, Dataset


def from_items(items: Sequence[Any], *, block_size: int = DEFAULT_BLOCK_SIZE) -> Dataset:
    blocks = blocks_from_rows(list(items), block_size)
    return Dataset([(lambda b=b: b) for b in blocks])


def range_(n: int, *, block_size: int = DEFAULT_BLOCK_SIZE) -> Dataset:
    def make_read(start: int, end: int):
        return lambda: {VALUE_COL: np.arange(start, end, dtype=np.int64)}

    return Dataset(
        [make_read(s, min(n, s + block_size)) for s in range(0, n, block_size)]
    )


def from_numpy(arr: "np.ndarray", *, block_size: int = DEFAULT_BLOCK_SIZE) -> Dataset:
    # Bind each task's SLICE, not the whole array: a closure over ``arr``
    # would ship the full array with every per-block remote task.
    def make_read(chunk: "np.ndarray"):
        return lambda: {VALUE_COL: chunk}

    n = len(arr)
    return Dataset(
        [make_read(arr[s : min(n, s + block_size)]) for s in range(0, n, block_size)]
    )


def from_pandas(df) -> Dataset:
    cols = {c: np.asarray(df[c].values) for c in df.columns}
    return Dataset([lambda: cols])


def from_arrow(table) -> Dataset:
    cols = {name: table.column(name).to_numpy(zero_copy_only=False) for name in table.column_names}
    return Dataset([lambda: cols])


def _expand_paths(paths: Union[str, Sequence[str]], suffix: str) -> List[str]:
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, f"*{suffix}"))))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no {suffix} files under {paths}")
    return out


def read_parquet(paths: Union[str, Sequence[str]], *, columns: Optional[List[str]] = None) -> Dataset:
    """One read task per file (reference parquet datasource)."""
    files = _expand_paths(paths, ".parquet")

    def make_read(path: str):
        def read() -> Dict[str, np.ndarray]:
            import pyarrow.parquet as pq

            table = pq.read_table(path, columns=columns)
            return {
                name: table.column(name).to_numpy(zero_copy_only=False)
                for name in table.column_names
            }

        return read

    return Dataset([make_read(f) for f in files])


def read_csv(paths: Union[str, Sequence[str]]) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def make_read(path: str):
        def read() -> Dict[str, np.ndarray]:
            import pyarrow.csv as pcsv

            table = pcsv.read_csv(path)
            return {
                name: table.column(name).to_numpy(zero_copy_only=False)
                for name in table.column_names
            }

        return read

    return Dataset([make_read(f) for f in files])


def read_numpy(paths: Union[str, Sequence[str]]) -> Dataset:
    """Reads ``.npy`` (one array → the value column) and ``.npz``
    (NumpySink output: one entry per block column)."""
    try:
        files = _expand_paths(paths, ".npy")
    except FileNotFoundError:
        files = _expand_paths(paths, ".npz")

    def make_read(path: str):
        def read():
            loaded = np.load(path)
            if isinstance(loaded, np.lib.npyio.NpzFile):
                return {k: loaded[k] for k in loaded.files}
            return {VALUE_COL: loaded}

        return read

    return Dataset([make_read(f) for f in files])


def read_json(paths: Union[str, Sequence[str]]) -> Dataset:
    """JSON-lines files, one read task per file (reference json
    datasource). Rows may be objects (become columns) or scalars
    (become the value column)."""
    files = _expand_paths(paths, ".json")

    def make_read(path: str):
        def read():
            import json

            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
            blocks = blocks_from_rows(rows, max(1, len(rows)))
            return blocks[0] if blocks else {VALUE_COL: np.asarray([])}

        return read

    return Dataset([make_read(f) for f in files])


class Datasource:
    """Custom-source ABC (reference
    ``data/datasource/datasource.py``): implement ``get_read_tasks(n)``
    returning no-arg callables, each producing one block."""

    def get_read_tasks(self, parallelism: int):
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


def read_datasource(source: Datasource, *, parallelism: int = 8) -> Dataset:
    tasks = list(source.get_read_tasks(parallelism))
    if not tasks:
        raise ValueError("datasource produced no read tasks")
    return Dataset(tasks)
