"""Distributed shuffle: a two-phase map/reduce exchange over tasks.

Reference: ``data/_internal/planner/exchange/push_based_shuffle_task_scheduler.py:590``
and ``shuffle_task_scheduler``. The driver orchestrates REFS ONLY — no
block bytes ever pass through it (the round-4 implementation
concatenated the whole dataset on the driver; this replaces it):

  map phase    one task per input block: assign each row a random
               output partition (seeded per block) and return the
               ``num_output_blocks`` partitions as SEPARATE return
               values, so each reducer fetches exactly its slice
               (an all-to-all over the object store's chunked
               node-to-node transfer).
  reduce phase one task per output block: concat its partition from
               every map task, then permute rows locally (seeded).

Memory: each reducer materializes one output block (~dataset/N), the
store holds the partition working set and spills under pressure — the
driver's footprint stays O(refs). Determinism: fixing ``seed`` fixes
the permutation for a given block structure.

Wire: the all-to-all is refs-only at this layer; the partition BYTES
move when each reducer's arg-fetch pulls its slices through the
daemon↔daemon chunk transfer, which since the zero-copy data plane PR
rides RAW frames end to end — sender segments scatter-gather onto the
socket, receivers land chunks straight in the destination segment
(``core/rpc.py`` kind 5, ``core/pull_manager.py``). ``bench.py``'s
``shuffle_gbps`` phase measures this exchange across a 2-node cluster;
``raytpu_shuffle_*`` counters surface exchange activity on /metrics.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, block_concat, block_num_rows, block_take
from ray_tpu.observability.metrics import Counter

#: exchanges orchestrated by this driver process
SHUFFLE_EXCHANGES = Counter(
    "raytpu_shuffle_exchanges_total",
    "shuffle exchanges orchestrated (driver-side)",
)
#: map-side partitions produced across all exchanges (n_in × n_out per
#: exchange) — each is one ref a reducer fetches over the RAW data plane
SHUFFLE_PARTITIONS = Counter(
    "raytpu_shuffle_partitions_total",
    "map partitions produced by shuffle exchanges (each fetched by a reducer)",
)


def _shuffle_map(block: Block, n_out: int, seed: int):
    """Split one block's rows into n_out random partitions."""
    n = block_num_rows(block)
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n_out, size=n)
    parts = tuple(
        block_take(block, np.nonzero(assign == j)[0]) for j in range(n_out)
    )
    return parts if n_out > 1 else parts[0]


def _shuffle_reduce(seed: int, *parts: Block) -> Block:
    merged = block_concat(list(parts))
    n = block_num_rows(merged)
    if n == 0:
        return merged
    rng = np.random.default_rng(seed)
    return block_take(merged, rng.permutation(n))


_map_remote = None
_reduce_remote = None


def _remotes():
    global _map_remote, _reduce_remote
    if _map_remote is None:
        _map_remote = ray_tpu.remote(num_cpus=1)(_shuffle_map)
        _reduce_remote = ray_tpu.remote(num_cpus=1)(_shuffle_reduce)
    return _map_remote, _reduce_remote


def shuffle_exchange(
    block_refs: List[object],
    *,
    num_output_blocks: Optional[int] = None,
    seed: Optional[int] = None,
) -> List[object]:
    """Run the exchange; returns the shuffled output block REFS."""
    if not block_refs:
        return []
    n_out = num_output_blocks or len(block_refs)
    SHUFFLE_EXCHANGES.inc()
    SHUFFLE_PARTITIONS.inc(len(block_refs) * n_out)
    base = seed if seed is not None else np.random.SeedSequence().entropy % (2**31)
    mapper, reducer = _remotes()
    map_outs = [
        mapper.options(num_returns=n_out).remote(ref, n_out, int(base) + i)
        for i, ref in enumerate(block_refs)
    ]
    if n_out == 1:
        # options(num_returns=1) yields a single ref, not a list
        map_cols = [[r] for r in map_outs]
    else:
        map_cols = map_outs
    return [
        reducer.remote(int(base) + 100003 + j, *[m[j] for m in map_cols])
        for j in range(n_out)
    ]
