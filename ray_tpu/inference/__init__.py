"""ray_tpu.inference — TPU-native LLM inference engine.

Continuous batching over a paged KV cache (vLLM-style), with bucketed
fixed-shape jitted prefill/decode steps, admission control, priority
preemption, and streaming Serve integration:

    from ray_tpu import serve
    from ray_tpu.inference import EngineConfig, llm_deployment

    handle = serve.run(llm_deployment(model_cfg, engine=EngineConfig()).bind())
    for tok in handle.stream({"prompt": [1, 2, 3]}, _method="generate"):
        ...

Submodules import lazily (PEP 562): ``kv_cache`` and ``scheduler`` are
pure python, but ``engine``/``model_runner`` pull in jax — control-plane
processes importing ``ray_tpu.inference`` for the scheduler must not pay
for (or require) a working jax.
"""

from __future__ import annotations

_LAZY = {
    "PagedBlockManager": "ray_tpu.inference.kv_cache",
    "ContinuousBatchingScheduler": "ray_tpu.inference.scheduler",
    "Request": "ray_tpu.inference.scheduler",
    "StepPlan": "ray_tpu.inference.scheduler",
    "EngineConfig": "ray_tpu.inference.engine",
    "InferenceEngine": "ray_tpu.inference.engine",
    "EngineDrainingError": "ray_tpu.inference.engine",
    "RequestFailedError": "ray_tpu.inference.engine",
    "PagedModelRunner": "ray_tpu.inference.model_runner",
    "llm_deployment": "ray_tpu.inference.serve_llm",
    "LLMServer": "ray_tpu.inference.serve_llm",
}

# jax-free names only: star-imports resolve every __all__ entry through
# __getattr__, and engine/model_runner/serve_llm pull in jax — the same
# hazard serve.__all__ guards against. The jax-backed names stay
# reachable by attribute.
__all__ = [
    "PagedBlockManager",
    "ContinuousBatchingScheduler",
    "Request",
    "StepPlan",
]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
