"""InferenceEngine: continuous-batching autoregressive generation.

One engine per replica/process. A background step-loop thread drives
``step()``: each step runs at most one prefill chunk plus the standing
decode batch (``scheduler.StepPlan``), samples the new tokens host-side,
and pushes them into per-request queues that :meth:`generate` drains —
so tokens stream to the caller WHILE other requests keep decoding.

Request lifecycle hooks the rest of the runtime:

* **deadlines** — ``submit`` captures the ambient ``core.deadline``
  budget (propagated onto TaskSpecs by the runtime, so a serve caller's
  timeout reaches the replica); the scheduler fails requests the step
  after their budget expires instead of decoding dead tokens.
* **drain** — ``begin_drain()`` stops admission and lets in-flight work
  finish inside ``drain_grace_s``; wired to the node DRAINING push via
  :meth:`attach_node_drain_listener` so a preemption warning on the
  replica's node stops new work without erroring live streams.
* **observability** — TTFT / tokens-per-second / cache-utilization /
  queue-depth gauges through ``observability.metrics`` and a per-step
  ``timeline`` profile event (chrome://tracing shows prefill/decode
  interleave per step).
* **deterministic continuation** — sampling is keyed on
  ``(request seed, absolute position)`` (:meth:`_sample`), so a request
  resubmitted with ``prompt + generated[:k]`` continues the identical
  token stream on ANY engine with the same params. That property is
  what the serve router's resumable-stream protocol (exactly-once token
  delivery across replica death) is built on.
* **chaos + health** — ``testing_replica_chaos`` installs a seeded
  :class:`util.chaos.ReplicaFaultPlan` consulted at the step boundary
  (kill mid-prefill/mid-decode, stall); :meth:`healthy` exposes a
  wedged-step-loop detector the serve controller polls through
  ``replica.health()``.
"""

from __future__ import annotations

import hashlib
import logging
import math
import os
import queue
import signal
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.deadline import Deadline, remaining as deadline_remaining
from ray_tpu.inference.kv_cache import PagedBlockManager, _chain_digest
from ray_tpu.inference.scheduler import (
    CANCELLED,
    DECODE,
    FAILED,
    FINISHED,
    ContinuousBatchingScheduler,
    Request,
)
from ray_tpu.observability import timeline
from ray_tpu.observability import tracing as _tracing

_END = object()  # stream sentinel

logger = logging.getLogger(__name__)

# -- replica chaos (util/chaos.py::ReplicaFaultPlan) -------------------------
_RPLAN_CACHE = None
_RPLAN_CACHE_LOCK = threading.Lock()


def active_replica_fault_plan():
    """The process-wide seeded replica fault plan for
    ``testing_replica_chaos`` (or None); seed logged at activation
    (util/chaos.py::SeededPlanCache)."""
    global _RPLAN_CACHE
    if _RPLAN_CACHE is None:
        from ray_tpu.util.chaos import ReplicaFaultPlan, SeededPlanCache

        with _RPLAN_CACHE_LOCK:
            if _RPLAN_CACHE is None:
                _RPLAN_CACHE = SeededPlanCache(
                    ReplicaFaultPlan, "replica",
                    "testing_replica_chaos", "testing_replica_chaos_seed",
                    logger,
                )
    return _RPLAN_CACHE.active()


def _model_kv_namespace(model_cfg, params) -> str:
    """Model-identity namespace for cluster KV tier keys. A chain
    digest names a TOKEN prefix, not the model that computed the KV —
    and the daemon tier registry is node-global — so tier keys are
    scoped by a fingerprint of (config, weights): the model config's
    repr plus, per weight leaf, its path, shape, dtype and a
    first-elements value sample. Two deployments of the same
    architecture with different weights therefore can never serve each
    other's KV (their shapes/dtypes are identical — only the values
    differ, which is exactly what the sample catches). Replicas of ONE
    deployment agree because param init is bit-deterministic (fixed
    seed; PR 14) and checkpoint loads share bytes."""
    h = hashlib.blake2b(digest_size=8)
    h.update(repr(model_cfg).encode())
    try:
        import jax

        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
            sample = np.ascontiguousarray(np.asarray(leaf.reshape(-1)[:4]))
            h.update(str(path).encode())
            h.update(str(np.shape(leaf)).encode())
            h.update(str(sample.dtype).encode())
            h.update(sample.tobytes())
    except Exception:  # noqa: BLE001 — the config repr alone still scopes
        pass
    return h.hexdigest()


def _stable_request_seed(request_id: str) -> int:
    """Process-independent sampling seed derived from a request id.
    ``hash()`` is salted per interpreter (PYTHONHASHSEED), which would
    make a request resumed on another replica sample a DIFFERENT stream
    — breaking exactly-once token delivery for unseeded requests."""
    return int.from_bytes(
        hashlib.blake2b(request_id.encode(), digest_size=8).digest(), "little"
    )


class EngineDrainingError(RuntimeError):
    """New request rejected because the engine is draining."""


class RequestFailedError(RuntimeError):
    """The engine gave up on a request (deadline expiry, drain cutoff)."""


#: string marker the serve router's resumable-stream failover matches on
#: (the exception type itself may be re-raised under a different class
#: after crossing the actor boundary — the message survives any wrapper);
#: defined in jax-free kv_transfer so routers can match it without
#: importing the engine
from ray_tpu.inference.kv_transfer import KV_MIGRATION_MARKER  # noqa: E402


class KvMigrationHandoff(RequestFailedError):
    """A draining replica flushed this in-flight request's FULL KV
    (prompt + generated) into the cluster tier and handed the stream
    back: the router resumes it on a survivor, which faults the KV in
    instead of re-prefilling — client-invisible through the SeqGate."""


@dataclass
class EngineConfig:
    """Knobs for the paged-KV continuous-batching engine (see README
    "inference" section)."""

    #: device block pool size (block 0 is the reserved null block)
    num_blocks: int = 128
    #: token positions per block
    block_size: int = 16
    #: prefill chunk-length buckets; one XLA program compiles per bucket.
    #: None → derived from the model's max_seq_len (powers of two).
    prefill_buckets: Optional[Sequence[int]] = None
    #: decode batch-size buckets; None → (1, 2, 4, ..., max_decode_batch)
    decode_buckets: Optional[Sequence[int]] = None
    max_decode_batch: int = 8
    #: prefill chunks per engine step (prefill rides WITH the decode batch)
    max_prefills_per_step: int = 1
    #: admission queue bound: submits beyond this fail fast
    max_queue_depth: int = 128
    #: compile every bucket at startup so serving never eats a compile
    warmup: bool = True
    #: default cap on generated tokens per request
    max_new_tokens_default: int = 64
    #: KV cache dtype override (None → model dtype)
    cache_dtype: Any = None
    #: reap finished-but-never-drained token streams after this long; a
    #: caller that submits and walks away (own deadline hit, gave up
    #: after a tokens() timeout without cancel()) would otherwise pin its
    #: queue in the replica forever. <= 0 disables.
    finished_stream_ttl_s: float = 300.0
    #: healthy() reports False once there is pending work but the step
    #: loop hasn't completed an iteration for this long — a wedged step
    #: thread (stuck device call, injected stall) in a replica whose
    #: actor loop still answers RPCs. The serve controller polls this
    #: through replica.health() and restarts the replica. <= 0 disables
    #: the staleness check (thread liveness is still checked).
    step_stall_unhealthy_s: float = 10.0
    #: prefix caching (kv_cache.py): full blocks are indexed by token
    #: chain-hash and SHARED with later requests whose prompt prefix
    #: matches — those skip the covered prefill chunks entirely (the
    #: warm-TTFT path for fleets of conversations sharing one system
    #: prompt). Numerically inert: shared KV values are exactly what an
    #: uncached prefill would have written.
    prefix_cache_enabled: bool = True
    #: cap on indexed blocks (0 = bounded only by the pool; unreferenced
    #: cached blocks are reclaimed LRU-first whenever allocation needs
    #: them, so the cache never starves admission)
    prefix_cache_max_blocks: int = 0
    #: KV-cache migration (disaggregated prefill/decode serving): opts
    #: this engine into the block gather/scatter programs — compiled at
    #: warmup so migrations never recompile — and the export/import
    #: request modes (prefill_kv / import_kv_blocks). Off by default so
    #: plain deployments keep their exact compile count.
    kv_transfer_enabled: bool = False
    #: cluster-wide KV prefix tier (kv_transfer.py tier layer): write
    #: popular full prefix blocks back into daemon-owned shm storage
    #: (explicitly at prefill/decode block boundaries, and as the SPILL
    #: half of the eviction spill-vs-drop policy), advertise them
    #: through the routing gossip, and serve warm recovery — resume via
    #: fault-in, warm replica restart, drain-time live migration.
    #: Implies the gather/scatter programs (kv_transfer warmup). Off by
    #: default so plain deployments keep their exact compile count.
    kv_tier_enabled: bool = False
    #: speculative decoding (inference/speculative.py): drafts proposed
    #: per decode slot and verified in ONE bucketed jitted target step
    #: (models.llama.paged_verify_step). 0 disables — plain deployments
    #: keep their exact compile count (no verify bucket, no draft
    #: runner). Acceptance is exact-match against the engine's own
    #: deterministic (seed, absolute-position) sampler, so the emitted
    #: stream is byte-identical to non-speculative decode and the
    #: resumable-stream contract survives unchanged.
    speculative_k: int = 0
    #: draft mode: "ngram" (model-free prompt-lookup decoding, zero
    #: device cost) or "model" (a scaled-down same-tokenizer draft model
    #: on its own paged runner; requires draft_config)
    speculative_draft: str = "ngram"
    #: LlamaConfig for speculative_draft="model" (same vocab as the
    #: target); ignored for "ngram"
    draft_config: Any = None
    #: draft model params (None → deterministic init from draft_config
    #: with draft_seed)
    draft_params: Any = None
    draft_seed: int = 0
    #: draft runner pool/buckets (0/None → scaled from the engine's own)
    draft_num_blocks: int = 0
    draft_prefill_buckets: Optional[Sequence[int]] = None
    #: adaptive k: the 4 Hz gauge refresh shrinks the live draft budget
    #: toward 1 while the windowed acceptance rate sits below the floor,
    #: and grows it back toward speculative_k while acceptance is high —
    #: the verify bucket stays fixed at speculative_k+1 (shorter windows
    #: pad via true_len), so adaptation never recompiles
    speculative_adaptive: bool = True
    speculative_accept_floor: float = 0.35
    #: prompt-lookup n-gram sizes for speculative_draft="ngram"
    ngram_max: int = 3
    ngram_min: int = 1

    def resolved_verify_buckets(self) -> Sequence[int]:
        """One verify bucket, sized for the full draft budget: k+1
        window positions (last committed token + k drafts); shorter
        windows (adaptive shrink, tail-of-request clamps) pad into it
        via true_len instead of compiling new shapes."""
        if self.speculative_k <= 0:
            return ()
        return (self.speculative_k + 1,)

    def resolved_prefill_buckets(self, max_seq_len: int) -> Sequence[int]:
        if self.prefill_buckets is not None:
            return tuple(sorted(self.prefill_buckets))
        out, b = [], 16
        while b < max_seq_len:
            out.append(b)
            b *= 2
        out.append(max_seq_len)
        return tuple(out)

    def resolved_decode_buckets(self) -> Sequence[int]:
        if self.decode_buckets is not None:
            return tuple(sorted(self.decode_buckets))
        out, b = [], 1
        while b < self.max_decode_batch:
            out.append(b)
            b *= 2
        out.append(self.max_decode_batch)
        return tuple(sorted(set(out)))


# -- engine metrics (registered once per process; re-registration of the
# same names returns the shared underlying metric) --------------------------


def _engine_metrics():
    from ray_tpu.observability.metrics import Counter, Gauge
    from ray_tpu.observability.slo import slo_metrics
    from ray_tpu.observability import rpc_metrics

    slo = slo_metrics()
    return {
        # SLO-ledger sinks (observability/slo.py): aggregatable
        # log-bucket histograms + goodput/fault-cost counters, labeled
        # {deployment, tenant_class}. raytpu_llm_ttft_seconds used to be
        # a per-engine quantile GAUGE — mathematically un-aggregatable
        # across a /federate scrape; the histogram replaces it.
        "ttft": slo["ttft"],
        "itl": slo["itl"],
        "e2e": slo["e2e"],
        "goodput": slo["goodput"],
        "fault": slo["fault"],
        "deadline": slo["deadline"],
        "tps": Gauge(
            "raytpu_llm_tokens_per_s",
            "decode throughput over the trailing window",
        ),
        "cache_util": Gauge(
            "raytpu_llm_kv_cache_utilization",
            "fraction of usable KV blocks currently allocated",
        ),
        "queue_depth": Gauge(
            "raytpu_llm_queue_depth", "requests waiting for admission"
        ),
        "active": Gauge("raytpu_llm_active_requests", "admitted, unfinished"),
        "decode_batch": Gauge(
            "raytpu_llm_decode_batch_size", "slots in the last decode step"
        ),
        "tokens_total": Counter(
            "raytpu_llm_tokens_generated_total", "tokens sampled"
        ),
        "requests_total": Counter(
            "raytpu_llm_requests_total", "requests by terminal state", ("outcome",)
        ),
        "preemptions_total": Counter(
            "raytpu_llm_preemptions_total", "requests evicted for blocks"
        ),
        "prefix_hits_total": Counter(
            "raytpu_llm_prefix_hits_total",
            "admissions that reused cached prefix blocks",
        ),
        "prefix_tokens_saved_total": Counter(
            "raytpu_llm_prefix_tokens_saved_total",
            "prompt tokens whose prefill was skipped via the prefix cache",
        ),
        "cow_copies_total": Counter(
            "raytpu_llm_cow_copies_total",
            "copy-on-write block duplications (full-prompt cache hits)",
        ),
        # speculative decoding (defined in rpc_metrics so every process
        # that imports the transport layer exports consistent help text;
        # referencing them here puts them on the engine /metrics path
        # and under the catalog lint)
        "spec_proposed": rpc_metrics.LLM_SPEC_PROPOSED,
        "spec_accepted": rpc_metrics.LLM_SPEC_ACCEPTED,
        "spec_rollbacks": rpc_metrics.LLM_SPEC_ROLLBACKS,
        "spec_acceptance": rpc_metrics.LLM_SPEC_ACCEPTANCE,
    }


class InferenceEngine:
    def __init__(self, model_cfg, params, engine_cfg: Optional[EngineConfig] = None):
        from ray_tpu.inference.model_runner import PagedModelRunner

        self.cfg = model_cfg
        self.engine_cfg = ec = engine_cfg or EngineConfig()
        decode_buckets = ec.resolved_decode_buckets()
        if ec.max_decode_batch > max(decode_buckets):
            # catching this at runtime instead means _round_up_bucket
            # raises inside step() and _fail_all errors every in-flight
            # request, repeatedly — fail loud at init instead
            raise ValueError(
                f"max_decode_batch={ec.max_decode_batch} exceeds the largest "
                f"decode bucket {max(decode_buckets)}; add a bucket >= the "
                "batch cap or lower max_decode_batch"
            )
        #: model-identity namespace scoping this engine's tier keys
        #: (REVIEW: the digest names tokens, the daemon registry is
        #: node-global — unscoped, one model could serve another's KV).
        #: Computed BEFORE runner construction: donation may invalidate
        #: the params tree the fingerprint samples.
        self._tier_ns = ""
        if ec.kv_tier_enabled:
            self._tier_ns = GLOBAL_CONFIG.kv_tier_namespace or _model_kv_namespace(
                model_cfg, params
            )
        self.runner = PagedModelRunner(
            model_cfg,
            params,
            num_blocks=ec.num_blocks,
            block_size=ec.block_size,
            prefill_buckets=ec.resolved_prefill_buckets(model_cfg.max_seq_len),
            decode_buckets=decode_buckets,
            verify_buckets=ec.resolved_verify_buckets(),
            cache_dtype=ec.cache_dtype,
        )
        self.blocks = PagedBlockManager(
            ec.num_blocks,
            ec.block_size,
            prefix_cache_enabled=ec.prefix_cache_enabled,
            prefix_cache_max_blocks=ec.prefix_cache_max_blocks,
        )
        self.scheduler = ContinuousBatchingScheduler(
            self.blocks,
            max_decode_batch=ec.max_decode_batch,
            max_prefill_chunk=max(ec.resolved_prefill_buckets(model_cfg.max_seq_len)),
            max_prefills_per_step=ec.max_prefills_per_step,
            max_queue_depth=ec.max_queue_depth,
        )
        self._out: Dict[str, queue.Queue] = {}
        # request id -> submitter's (trace_id, span_id): the step-loop
        # thread stamps per-request spans (admission→first-token,
        # admission→finish) under the serve caller's trace
        self._trace_ctx: Dict[str, tuple] = {}
        self._submitted_at: Dict[str, float] = {}
        self._first_token_at: Dict[str, float] = {}
        self._finished_at: Dict[str, float] = {}
        self._next_stream_reap = 0.0
        self._next_gauge_refresh = 0.0
        self._lock = threading.RLock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self._drain_deadline: Optional[Deadline] = None
        self._listener_backend = None
        self._node_listener = None
        #: step-loop heartbeat consumed by healthy(): stamped once per
        #: loop iteration, so a step wedged inside device code (or an
        #: injected stall) goes stale while the actor loop stays live
        self._last_beat = time.monotonic()
        #: per-engine fault-plan override (tests arm ONE replica
        #: surgically); None falls through to the env/config plan
        self.testing_fault_plan = None
        self.metrics = _engine_metrics()
        from ray_tpu.observability.slo import BucketCounts

        #: per-ENGINE TTFT tape (the process-registry histogram is shared
        #: by every engine in the process — tests host several): backs
        #: the stats()["ttft"] p50/p99 back-compat shape
        self._ttft_tape = BucketCounts()
        #: deployment label for the SLO series; serve/replica.py stamps
        #: it via LLMServer.set_deployment_name ("" for bare engines)
        self.slo_deployment = ""
        #: intake books — with the scheduler's queued/running counts,
        #: submitted == finished + failed + cancelled + in_flight holds
        #: exactly at quiesce (slo.books_balanced), the conservation gate
        #: fault paths are reconciled against
        self._books = {"submitted": 0, "finished": 0, "failed": 0, "cancelled": 0}
        self._token_times: deque = deque(maxlen=2048)
        #: recent (monotonic, value) latency samples backing the gossiped
        #: closed-loop signals (routing_stats ttft_p99_s / itl_p99_s).
        #: The ledger tapes above are LIFETIME histograms — an autopilot
        #: steering on them would barely feel current burn, so the
        #: control signals come from a sliding window instead.
        self._recent_ttfts: deque = deque(maxlen=512)
        self._recent_itls: deque = deque(maxlen=2048)
        #: (monotonic, n_tokens) per prefill pass — windowed prefill
        #: throughput for the disagg pool-ratio adaptation
        self._prefill_token_times: deque = deque(maxlen=2048)
        self._preempt_seen = 0
        self._replay_seen = 0
        self._prefix_seen: Dict[str, int] = {}
        #: queued KV-import jobs, executed BY the step thread at the top
        #: of each step — device cache mutation must never race the step
        #: loop's own cache swaps (donation on TPU invalidates the buffer
        #: a concurrent reader grabbed)
        self._kv_imports: "queue.Queue" = queue.Queue()
        # -- cluster KV tier (PR 17) --
        #: tier adverts this replica gossips: digest hex -> routable
        #: descriptor, MRU-capped at kv_tier_max_adverts. Dropping an
        #: entry here IS the retraction signal — routers diff advert
        #: sets per report and purge in one gossip hop.
        self._tier_adverts: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        #: guards _tier_adverts and _tier_pending: mutated by the step
        #: thread AND the tier publisher thread, snapshotted by
        #: routing_stats on the actor thread — an unlocked OrderedDict
        #: move_to_end/popitem races "mutated during iteration" there
        self._tier_lock = threading.Lock()
        #: digests queued for background publish (dedup vs re-enqueue)
        self._tier_pending: set = set()
        #: (digest, host kv, trigger) handed to the tier publisher
        #: thread. Gathers stay ON the step thread (device cache reads
        #: must not race donation) but the publish — shm write + daemon
        #: RPC with a 10s timeout — must come OFF it: a wedged daemon
        #: would otherwise stall token emission for the whole batch at
        #: every block boundary. Bounded: overflow drops the write-back
        #: (best-effort warmth, never backpressure on decode).
        self._tier_pub_q: "queue.Queue" = queue.Queue(maxsize=256)
        self._tier_pub_thread: Optional[threading.Thread] = None
        #: (digest, host kv) spills gathered under the block-manager
        #: lock, published by the step thread OUTSIDE it (publish does
        #: shm writes + daemon RPC — too heavy for an allocation path)
        self._tier_spill_pending: List[tuple] = []
        #: drain-with-migration latch (begin_drain(migrate=True))
        self._migrate_on_drain = False
        if ec.kv_tier_enabled:
            self.blocks.set_spill_hook(self._tier_spill)
        # -- speculative decoding (PR 19) --
        #: the draft proposer (None when disabled). Only constructed for
        #: speculative_k > 0, so plain engines keep their exact compile
        #: count — the verify jit exists but holds zero cache entries.
        self.spec = None
        #: lifetime propose/accept/rollback books (stats() + adaptive k)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_rollbacks = 0
        #: (proposed, accepted) snapshot at the last gauge refresh — the
        #: adaptive controller steers on the window delta, not lifetime
        self._spec_window_seen = (0, 0)
        self._spec_acceptance = 0.0
        if ec.speculative_k > 0:
            from ray_tpu.inference.speculative import (
                DraftModelProposer,
                NgramProposer,
            )

            if ec.speculative_draft == "model":
                if ec.draft_config is None:
                    raise ValueError(
                        "speculative_draft='model' requires draft_config"
                    )
                draft_params = ec.draft_params
                if draft_params is None:
                    import jax

                    from ray_tpu.models.llama import init_params

                    draft_params = init_params(
                        ec.draft_config, jax.random.PRNGKey(ec.draft_seed)
                    )
                self.spec = DraftModelProposer(
                    ec.draft_config,
                    draft_params,
                    num_blocks=ec.draft_num_blocks or ec.num_blocks,
                    block_size=ec.block_size,
                    prefill_buckets=(
                        tuple(ec.draft_prefill_buckets)
                        if ec.draft_prefill_buckets is not None
                        else ec.resolved_prefill_buckets(
                            ec.draft_config.max_seq_len
                        )
                    ),
                    cache_dtype=ec.cache_dtype,
                )
            elif ec.speculative_draft == "ngram":
                self.spec = NgramProposer(
                    max_ngram=ec.ngram_max, min_ngram=ec.ngram_min
                )
            else:
                raise ValueError(
                    f"unknown speculative_draft {ec.speculative_draft!r} "
                    "(expected 'ngram' or 'model')"
                )
            self.scheduler.spec_max_context = model_cfg.max_seq_len
            self.scheduler.spec_k_live = ec.speculative_k
        self.total_steps = 0
        if ec.warmup:
            self.runner.warmup(kv_io=ec.kv_transfer_enabled or ec.kv_tier_enabled)
            if self.spec is not None and hasattr(self.spec, "warmup"):
                self.spec.warmup()
        else:
            self.runner.mark_warm()
            if self.spec is not None and hasattr(self.spec, "mark_warm"):
                self.spec.mark_warm()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "InferenceEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="llm-engine-step"
            )
            self._thread.start()
        if self.engine_cfg.kv_tier_enabled:
            if self._tier_pub_thread is None or not self._tier_pub_thread.is_alive():
                self._tier_pub_thread = threading.Thread(
                    target=self._tier_publish_loop,
                    daemon=True,
                    name="llm-engine-tier-pub",
                )
                self._tier_pub_thread.start()
            self._tier_recover()
        return self

    def _tier_recover(self) -> None:
        """Warm-restart half of the tier: the local daemon's registry
        survived whatever killed the previous replica process — re-adopt
        its entries as OUR adverts so the very next gossip beat makes
        this replacement routable as prefix-warm. Failover stall then
        ≈ fault-in pull latency, not a cold prefill. Filtered to OUR
        model namespace: the registry is node-global, and re-adverting
        another deployment's entries would route its KV to our model."""
        try:
            from ray_tpu.inference import kv_transfer

            entries = kv_transfer.tier_list(ns=self._tier_ns)
        except Exception:  # noqa: BLE001 — recovery is best-effort
            return
        cap = max(1, GLOBAL_CONFIG.kv_tier_max_adverts)
        with self._tier_lock:
            for digest_hex, desc in entries.items():
                if len(self._tier_adverts) >= cap:
                    break
                self._tier_adverts[digest_hex] = desc

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._tier_pub_thread is not None:
            self._tier_pub_thread.join(timeout=10)
            self._tier_pub_thread = None
        # the step loop is dead: queued/running requests can never emit
        # another token — fail them so callers blocked in tokens() wake
        # instead of hanging on q.get() forever
        self._fail_all(RequestFailedError("engine stopped"))
        # parked KV importers would likewise wait on a thread that will
        # never run their job again
        while True:
            try:
                _tokens, _kv, reply = self._kv_imports.get_nowait()
            except queue.Empty:
                break
            reply.put((False, RequestFailedError("engine stopped")))
        self.detach_node_drain_listener()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._last_beat = time.monotonic()
            did_work = False
            try:
                did_work = self.step()
            except Exception as e:  # noqa: BLE001 — fail in-flight, keep serving
                self._fail_all(e)
            self._reap_abandoned_streams()
            if not did_work:
                self._work.wait(timeout=0.005)
                self._work.clear()

    # -- submission -------------------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        temperature: float = 0.0,
        priority: int = 0,
        eos_token: Optional[int] = None,
        request_id: Optional[str] = None,
        seed: Optional[int] = None,
        timeout_s: Optional[float] = None,
        prefill_only: bool = False,
        tenant_class: str = "",
        ledger_stages: Optional[Dict[str, float]] = None,
        record_slo: bool = True,
        speculative: Optional[bool] = None,
    ) -> str:
        """Enqueue a generation request; returns its id. The ambient
        ``core.deadline`` budget (or explicit ``timeout_s``, whichever is
        tighter) bounds the request end to end. ``prefill_only`` is the
        KV-migration export mode (use :meth:`prefill_kv`, which also
        drains the payload). ``tenant_class`` labels the SLO histograms;
        ``ledger_stages`` carries stage durations measured upstream
        (e.g. the KV import that ran before this submit);
        ``record_slo=False`` keeps a resume attempt's warm-replay
        latencies out of the SLO histograms (see Request.record_slo).
        ``speculative`` is the per-request off-switch: False forces
        plain decode for this request even on a speculative engine
        (True/None follow the engine config — output bytes are
        identical either way, only throughput changes)."""
        if self._draining or not self.scheduler.admitting:
            raise EngineDrainingError("engine is draining: not admitting requests")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens is None:
            max_new = self.engine_cfg.max_new_tokens_default
        else:
            max_new = int(max_new_tokens)
            if max_new < 1:
                raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        # clamp so prompt + generation always fits the block-table width
        room = self.cfg.max_seq_len - len(prompt)
        if room < 1:
            raise ValueError(
                f"prompt of {len(prompt)} tokens >= max_seq_len {self.cfg.max_seq_len}"
            )
        max_new = min(max_new, room)
        rid = request_id or uuid.uuid4().hex[:16]
        if temperature > 0.0 and seed is None:
            # resolve ONCE, stably: sampling is keyed on (seed, position)
            # so a resumed/replayed request re-derives the identical
            # stream from its id alone (see _sample / _stable_request_seed)
            seed = _stable_request_seed(rid)
        budget = deadline_remaining()
        if timeout_s is not None:
            budget = timeout_s if budget is None else min(budget, timeout_s)
        req = Request(
            request_id=rid,
            prompt=prompt,
            max_new_tokens=max_new,
            priority=priority,
            temperature=temperature,
            eos_token=eos_token,
            deadline=Deadline.after(budget) if budget is not None else None,
            seed=seed,
            prefill_only=prefill_only,
            tenant_class=str(tenant_class or ""),
            ledger_stages=dict(ledger_stages or {}),
            record_slo=bool(record_slo),
            spec_k=(
                self.engine_cfg.speculative_k
                if self.spec is not None
                and speculative is not False
                and not prefill_only
                else 0
            ),
        )
        trace_wire = _tracing.current_wire()
        with self._lock:
            if rid in self._out:
                raise ValueError(f"duplicate request_id {rid!r}")
            self._out[rid] = queue.Queue()
            if trace_wire is not None:
                self._trace_ctx[rid] = trace_wire
            self._submitted_at[rid] = time.monotonic()
        try:
            self.scheduler.add(req)
        except Exception:
            with self._lock:
                self._out.pop(rid, None)
                self._trace_ctx.pop(rid, None)
                self._submitted_at.pop(rid, None)
            raise
        with self._lock:
            # counted only AFTER scheduler.add succeeded: a rejected
            # submit (queue full, draining) never entered the books
            self._books["submitted"] += 1
        self._work.set()
        return rid

    def generate(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        temperature: float = 0.0,
        priority: int = 0,
        eos_token: Optional[int] = None,
        request_id: Optional[str] = None,
        seed: Optional[int] = None,
        timeout_s: Optional[float] = None,
        tenant_class: str = "",
        ledger_stages: Optional[Dict[str, float]] = None,
        record_slo: bool = True,
        speculative: Optional[bool] = None,
    ) -> Iterator[int]:
        """Submit and stream tokens as they decode. Closing/abandoning
        the iterator cancels the request and frees its blocks."""
        rid = self.submit(
            prompt,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            priority=priority,
            eos_token=eos_token,
            request_id=request_id,
            seed=seed,
            timeout_s=timeout_s,
            tenant_class=tenant_class,
            ledger_stages=ledger_stages,
            record_slo=record_slo,
            speculative=speculative,
        )
        try:
            yield from self.tokens(rid)
        finally:
            self.cancel(rid)  # no-op when already finished

    def generate_chunks(self, prompt: Sequence[int], **kw) -> Iterator[List[int]]:
        """:meth:`generate`, coalesced: yields LISTS — each the full
        burst of tokens available at wake-up. Speculative decoding
        commits up to k+1 tokens per verify step; draining the burst in
        one item lets the serve streaming path pay its per-item cost
        once per STEP instead of once per token (the router flattens, so
        clients still see a per-token stream)."""
        rid = self.submit(prompt, **kw)
        try:
            yield from self.tokens_chunked(rid)
        finally:
            self.cancel(rid)  # no-op when already finished

    def tokens_chunked(
        self, request_id: str, timeout: Optional[float] = None
    ) -> Iterator[List[int]]:
        """Chunked variant of :meth:`tokens`: one blocking wait per
        burst, then a non-blocking drain of everything already queued.
        Timeout/resume semantics match :meth:`tokens` (the timeout
        bounds the wait for the NEXT burst)."""
        q = self._out.get(request_id)
        if q is None:
            raise KeyError(f"unknown request {request_id!r}")
        drop = True
        try:
            while True:
                try:
                    item = q.get(timeout=timeout) if timeout is not None else q.get()
                except queue.Empty:
                    drop = False
                    raise TimeoutError(
                        f"no token within {timeout}s for request {request_id!r}; "
                        "still running — retry tokens_chunked() or cancel()"
                    ) from None
                terminal = None
                chunk: List[int] = []
                while True:
                    if item is _END or isinstance(item, Exception):
                        terminal = item
                        break
                    chunk.append(item)
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                if chunk:
                    yield chunk
                if terminal is _END:
                    return
                if terminal is not None:
                    raise terminal
        finally:
            # same queue-drop rule as tokens(): keep it on inter-token
            # timeout so a retry can pick the stream back up
            if drop:
                with self._lock:
                    self._out.pop(request_id, None)
                    self._finished_at.pop(request_id, None)

    def tokens(self, request_id: str, timeout: Optional[float] = None) -> Iterator[int]:
        """Drain a submitted request's token stream. ``timeout`` bounds
        each inter-token gap: on expiry a :class:`TimeoutError` is raised
        but the request keeps running and the stream stays resumable —
        call ``tokens()`` again to continue, or ``cancel()`` to give up."""
        q = self._out.get(request_id)
        if q is None:
            raise KeyError(f"unknown request {request_id!r}")
        drop = True
        try:
            while True:
                try:
                    item = q.get(timeout=timeout) if timeout is not None else q.get()
                except queue.Empty:
                    drop = False
                    raise TimeoutError(
                        f"no token within {timeout}s for request {request_id!r}; "
                        "still running — retry tokens() or cancel()"
                    ) from None
                if item is _END:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            # stream consumed (or abandoned): drop the queue — except on
            # inter-token timeout, where the request is still decoding and
            # a retry must find the queue (popping here would silently
            # drop every later token and KeyError the retry)
            if drop:
                with self._lock:
                    self._out.pop(request_id, None)
                    self._finished_at.pop(request_id, None)

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued/running request; frees its blocks. Returns
        True if something was actually cancelled."""
        req = self.scheduler.cancel(request_id)
        if req is None:
            # already finished (or unknown). The finish may still be
            # mid-flight on the step thread — scheduler.finish() done but
            # _finish_request() not yet run — so popping the queue alone
            # could strand a consumer blocked in q.get() with no _END
            # ever arriving. Wake it, then drop the dict entry.
            with self._lock:
                q = self._out.pop(request_id, None)
                self._finished_at.pop(request_id, None)
            if q is not None:
                q.put(_END)
            return False
        self._finish_request(req, CANCELLED, error=None)
        return True

    # -- drain ------------------------------------------------------------
    def begin_drain(
        self, grace_s: Optional[float] = None, *, migrate: bool = False
    ) -> None:
        """Stop admitting; in-flight (queued + running) requests keep
        decoding until done or the grace window closes, after which the
        stragglers fail with :class:`RequestFailedError`.

        ``migrate=True`` (tier deployments): instead of letting
        in-flight decodes run the grace window out, the next step
        flushes each one's FULL KV (prompt + generated — closing the
        disagg gap where export covers prompt KV only) into the cluster
        tier and fails it with :class:`KvMigrationHandoff`, which the
        router treats as resumable — the stream continues on a survivor
        via tier fault-in, client-invisible."""
        grace = GLOBAL_CONFIG.drain_grace_s if grace_s is None else grace_s
        with self._lock:
            self._draining = True
            self.scheduler.admitting = False
            self._drain_deadline = Deadline.after(grace)
            if migrate and self.engine_cfg.kv_tier_enabled:
                self._migrate_on_drain = True
        self._work.set()

    @property
    def draining(self) -> bool:
        return self._draining

    def attach_node_drain_listener(self) -> None:
        """Subscribe to node DRAINING pushes: a preemption warning on OUR
        node triggers ``begin_drain`` (serve unroutes the replica at the
        same time, so live streams finish and nothing new arrives)."""
        try:
            import ray_tpu
            from ray_tpu.core.api import _global_worker

            my_node = ray_tpu.get_runtime_context().get_node_id()
            backend = _global_worker().backend
        except Exception:
            return  # local mode / no cluster: explicit begin_drain() only

        def _on_node_event(msg: Dict[str, Any]) -> None:
            nid = msg.get("node_id")
            nid = nid.hex() if isinstance(nid, bytes) else nid
            if msg.get("state") == "DRAINING" and nid == my_node:
                self.begin_drain()

        try:
            backend.add_node_event_listener(_on_node_event)
        except Exception:
            return
        self._listener_backend = backend
        self._node_listener = _on_node_event

    def detach_node_drain_listener(self) -> None:
        if self._listener_backend is not None and self._node_listener is not None:
            try:
                self._listener_backend.remove_node_event_listener(self._node_listener)
            except Exception:
                pass
        self._listener_backend = None
        self._node_listener = None

    # -- the step ---------------------------------------------------------
    def step(self) -> bool:
        """One engine step: ≤N prefill chunks + the decode batch. Returns
        whether any work ran."""
        if self._draining and self._drain_deadline is not None and self._drain_deadline.expired:
            self._fail_all(
                RequestFailedError("engine drain grace expired mid-generation")
            )
        if self._migrate_on_drain:
            self._migrate_inflight()
        did_import = self._drain_kv_imports()
        self._drain_tier_spills()
        plan = self.scheduler.schedule()
        for req in plan.reaped:
            # every reap here is a deadline expiry (queued or running) —
            # a fault-cost class the SLO report breaks out explicitly
            self.metrics["deadline"].inc(
                labels={"deployment": self.slo_deployment}
            )
            self._finish_request(
                req,
                req.state,
                error=RequestFailedError(
                    f"request {req.request_id} deadline expired before completion"
                ),
            )
        if not plan.prefills and not plan.decodes:
            return did_import or not plan.empty
        self._consult_replica_chaos(plan)

        # timeline timestamps share the module's wall-clock epoch so
        # engine_step events merge with every other process's trace
        t0_us = timeline._now_us()
        n_prefill_tokens = 0
        for req, start, chunk in plan.prefills:
            if req.pending_cow:
                # prefix-cache COW: duplicate the shared block(s) BEFORE
                # this chunk writes into the private copies, then drop
                # the source pins (the copies are live in the table now)
                self.runner.copy_blocks(req.pending_cow)
                self.blocks.cow_copied(req.request_id)
                req.pending_cow = []
            row = self.blocks.table_row(req.request_id, self.runner.max_blocks_per_seq)
            prompt = req.effective_prompt
            logits = self.runner.prefill_chunk(
                prompt[start : start + chunk], row, start
            )
            req.prefill_pos = start + chunk
            n_prefill_tokens += chunk
            if req.prefill_done and req.prefill_done_at is None:
                req.prefill_done_at = time.monotonic()
            if req.prefill_done:
                # the prompt's K/V is fully written: index its full
                # blocks so later requests sharing the prefix skip them
                self.blocks.register_prefix(req.request_id, prompt)
                if self.engine_cfg.kv_tier_enabled and not req.prefill_only:
                    # tier write-back trigger 1: the prompt's full
                    # blocks become cluster-recoverable the moment they
                    # exist — a replica killed one token later already
                    # left its prefill in the tier
                    self._tier_writeback_full_blocks(req, prompt, "prefill")
                if req.prefill_only:
                    # KV-migration export: gather the full blocks to
                    # host and hand the payload to the waiting exporter
                    # — no token is ever sampled on this engine
                    self._complete_prefill_export(req, prompt)
                else:
                    req.state = DECODE
                    self._emit_token(req, self._sample(req, logits))

        if plan.decodes:
            # speculative slots peel off the batch: each proposes drafts,
            # then EVERY spec slot verifies in one batched target step
            # (models.llama.paged_verify_step: B slots x k+1 positions
            # per jit call). Slots whose proposer came up empty (no
            # n-gram match, draft pool dry) ride the plain batched
            # decode unchanged — speculation is an opportunistic
            # throughput lever, never a dependency.
            spec_slots: List[tuple] = []
            plain: List[Request] = []
            for r in plan.decodes:
                drafts = self._spec_propose(r) if r.spec_step_k > 0 else []
                if drafts:
                    spec_slots.append((r, drafts))
                else:
                    plain.append(r)
            if plain:
                toks = [r.generated[-1] for r in plain]
                poss = [r.context_len - 1 for r in plain]
                rows = [
                    self.blocks.table_row(
                        r.request_id, self.runner.max_blocks_per_seq
                    )
                    for r in plain
                ]
                cls = [r.context_len for r in plain]
                logits = self.runner.decode(toks, poss, rows, cls)
                for req, lg in zip(plain, logits):
                    self._emit_token(req, self._sample(req, lg))
            if spec_slots:
                windows = [[r.generated[-1]] + d for r, d in spec_slots]
                rows = [
                    self.blocks.table_row(
                        r.request_id, self.runner.max_blocks_per_seq
                    )
                    for r, _ in spec_slots
                ]
                ctxs = [r.context_len - 1 for r, _ in spec_slots]
                all_logits = self.runner.verify_batch(windows, rows, ctxs)
                for (req, drafts), logits in zip(spec_slots, all_logits):
                    self._spec_accept(req, drafts, logits)
        if n_prefill_tokens:
            self._prefill_token_times.append((time.monotonic(), n_prefill_tokens))
        self.total_steps += 1
        timeline.record_event(
            "engine_step",
            "inference",
            t0_us,
            timeline._now_us(),
            args={
                "prefill_tokens": n_prefill_tokens,
                "decode_batch": len(plan.decodes),
            },
        )
        self._update_gauges(len(plan.decodes))
        return True

    # -- speculative decoding (PR 19) -------------------------------------
    def _spec_propose(self, req: Request) -> List[int]:
        """Ask the proposer for up to ``spec_step_k`` drafts for this
        slot. An empty proposal (nothing to look up, draft pool dry, a
        broken proposer) degrades the slot to plain decode this step and
        hands back the blocks the scheduler grew for the draft window."""
        ctx = req.prompt + req.generated
        try:
            drafts = self.spec.propose(
                ctx, req.spec_step_k, request_id=req.request_id
            )
        except Exception:  # noqa: BLE001 — proposer bugs must not kill steps
            logger.exception("speculative proposer failed; plain decode")
            drafts = []
        drafts = [int(t) for t in list(drafts)[: req.spec_step_k]]
        if not drafts:
            self.blocks.trim_to(req.request_id, req.context_len)
        return drafts

    def _spec_accept(
        self, req: Request, drafts: List[int], logits: np.ndarray
    ) -> None:
        """Commit the deterministically-accepted prefix of one slot's
        verify window ``[last_committed, d_1..d_k']`` from its
        all-position target logits (``logits[i]`` is the distribution
        AFTER window position i; the batched verify already ran).

        Acceptance is exact-match: at each window position the target's
        token is realized with the engine's own (seed, absolute-position)
        sampler (:meth:`_sample` — ``pos`` advances naturally as tokens
        emit), drafts are accepted while they match it, and the first
        mismatch position emits the target's token INSTEAD (the
        bonus/correction token — every speculative step nets >= 1
        token). Emitted bytes are therefore identical to plain decode by
        construction, for greedy and seeded temperature>0 sampling
        alike, and the proposer can never affect content — only the
        acceptance rate.

        Rollback is pure host-side accounting: ``generated`` only ever
        received accepted tokens (the write cursor rewind is implicit),
        and :meth:`PagedBlockManager.trim_to` hands back the blocks
        grown past the committed context. The rejected tail's K/V stays
        stale on device, unreachable by construction — every masked
        read stops at the committed context length, and re-verification
        overwrites the slots in place. The prefix index and the KV tier
        only ever see positions below the verified cursor because both
        derive from ``generated``."""
        m = self.metrics
        accepted = 0
        for i in range(len(drafts) + 1):
            if req.finished:
                break
            tok = self._sample(req, logits[i])
            matched = i < len(drafts) and tok == drafts[i]
            self._emit_token(req, tok)
            if i < len(drafts):
                if not matched:
                    break
                accepted += 1
        self._spec_proposed += len(drafts)
        self._spec_accepted += accepted
        m["spec_proposed"].inc(len(drafts))
        if accepted:
            m["spec_accepted"].inc(accepted)
        if accepted < len(drafts):
            self._spec_rollbacks += 1
            m["spec_rollbacks"].inc()
        self.blocks.trim_to(req.request_id, req.context_len)

    # -- internals --------------------------------------------------------
    def _sample(self, req: Request, logits: np.ndarray) -> int:
        """Deterministic continuation: the RNG is keyed on
        ``(request seed, absolute position)`` instead of a stateful
        per-request stream. ``len(prompt) + len(generated)`` equals the
        original sequence position regardless of how much of the
        sequence arrived AS prompt — so a request resubmitted as
        ``prompt + generated[:k]`` provably samples token k+1
        identically, which is what makes mid-stream failover replay
        byte-exact (serve router resume; pinned by
        tests/test_stream_resume.py)."""
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        pos = len(req.prompt) + len(req.generated)
        seed = req.seed if req.seed is not None else 0
        rng = np.random.default_rng(
            np.random.SeedSequence([int(seed) & 0xFFFFFFFFFFFFFFFF, pos])
        )
        z = (logits / req.temperature).astype(np.float64)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(p), p=p))

    def _consult_replica_chaos(self, plan) -> None:
        """Replica fault injection at the step boundary (ReplicaFaultPlan):
        consulted once per phase this step actually runs, BEFORE the
        phase's device work — a kill lands after the last emitted token
        and before the next one samples, the boundary the router's
        seq-numbered resume must cover."""
        for phase, present in (
            ("prefill", bool(plan.prefills)),
            ("decode", bool(plan.decodes)),
        ):
            if present:
                self._consult_phase_chaos(phase)

    def _consult_phase_chaos(self, phase: str) -> None:
        """One chaos consult for a named engine phase ("prefill" |
        "decode" | "export" | "import" — the latter two are the
        KV-migration consult points: a kill there lands exactly
        mid-handoff, which the disagg fallback ladder must absorb)."""
        chaos = self.testing_fault_plan or active_replica_fault_plan()
        if chaos is None:
            return
        fault = chaos.consult(phase)
        if fault is None:
            return
        mode, param = fault
        if mode == "stall":
            logger.warning(
                "replica chaos: stalling step loop %.2fs (seed=%d)",
                param, chaos.seed,
            )
            time.sleep(param)
        else:
            logger.warning(
                "replica chaos: %s — SIGKILL self (pid=%d seed=%d)",
                mode, os.getpid(), chaos.seed,
            )
            os.kill(os.getpid(), signal.SIGKILL)

    # -- KV-cache migration (disaggregated serving) -----------------------
    def prefill_kv(
        self,
        prompt: Sequence[int],
        *,
        priority: int = 0,
        request_id: Optional[str] = None,
        timeout_s: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Export mode: run ONLY the prompt's prefill, then gather its
        FULL KV blocks to host and return the payload ``{"tokens":
        covered_tokens, "kv": np[2, L, n, bs, n_kv, hd], "block_size"}``
        — the migration unit ``inference/kv_transfer.py`` serializes and
        ships. Returns None when the prompt spans no full block (nothing
        exportable — the caller falls back to plain generation). The
        prefill itself also populates THIS engine's radix index, so an
        exporting replica keeps the warm-prefix benefit locally."""
        rid = self.submit(
            prompt,
            max_new_tokens=1,
            priority=priority,
            request_id=request_id,
            timeout_s=timeout_s,
            prefill_only=True,
        )
        q = self._out.get(rid)
        try:
            while True:
                item = q.get(timeout=timeout_s)
                if item is _END:
                    return None
                if isinstance(item, Exception):
                    raise item
                if isinstance(item, tuple) and item and item[0] == "kv_export":
                    return item[1]
        except queue.Empty:
            self.cancel(rid)
            raise TimeoutError(
                f"kv export of {len(prompt)} prompt tokens not done within "
                f"{timeout_s}s"
            ) from None
        finally:
            with self._lock:
                self._out.pop(rid, None)
                self._finished_at.pop(rid, None)

    def _complete_prefill_export(self, req: Request, prompt) -> None:
        """Step-thread half of :meth:`prefill_kv`: the gather MUST run
        here — the step loop swaps (and on TPU donates) the cache value
        every step, so a reader on another thread could hold an
        invalidated buffer."""
        self._consult_phase_chaos("export")
        bs = self.blocks.block_size
        n_full = len(prompt) // bs
        try:
            payload = None
            if n_full > 0:
                t0 = time.monotonic()
                blocks = self.blocks.owned(req.request_id)[:n_full]
                kv = self.runner.gather_blocks(blocks)
                # ledger stage: device→host gather time of the exported
                # blocks (the disagg handoff's engine-side cost)
                req.ledger_stages["kv_export"] = time.monotonic() - t0
                payload = {
                    "tokens": list(prompt[: n_full * bs]),
                    "kv": kv,
                    "block_size": bs,
                }
        except Exception as e:  # noqa: BLE001 — exporter must not hang
            if self.scheduler.finish(req, FAILED):
                req.state = FAILED
                self._finish_request(
                    req, FAILED,
                    error=RequestFailedError(f"kv export failed: {e!r}"),
                )
            return
        if payload is not None:
            with self._lock:
                q = self._out.get(req.request_id)
            if q is not None:
                q.put(("kv_export", payload))
        if self.scheduler.finish(req, FINISHED):
            self._finish_request(req, FINISHED, error=None)

    def import_kv_blocks(
        self, tokens: Sequence[int], kv, timeout_s: float = 30.0
    ) -> int:
        """Install migrated KV blocks into this engine's cache + radix
        index (the import half of KV migration). ``kv`` is the
        :meth:`prefill_kv` payload layout; block i must hold the K/V of
        ``tokens[i*bs:(i+1)*bs]``. Queued to the STEP THREAD (cache
        mutation must not race its swaps) and waited on here. Returns
        the number of prompt tokens now covered by the radix index —
        the immediately-following submit acquires them as a prefix hit.
        Raises on block-pool exhaustion or scatter failure (callers
        degrade to a plain prefill)."""
        bs = self.blocks.block_size
        n = min(len(tokens) // bs, int(kv.shape[2]))
        if n <= 0:
            return 0
        reply: "queue.Queue" = queue.Queue()
        self._kv_imports.put((list(tokens[: n * bs]), kv[:, :, :n], reply))
        self._work.set()
        try:
            ok, result = reply.get(timeout=timeout_s)
        except queue.Empty:
            raise TimeoutError(
                f"kv import of {n} blocks not executed within {timeout_s}s"
            ) from None
        if not ok:
            raise result
        return result

    def _drain_kv_imports(self) -> bool:
        """Step-thread executor for queued KV imports. Each job:
        reserve pinned blocks → device scatter → commit into the radix
        index (redundant blocks freed). All-or-nothing per job; failures
        surface to the waiting importer, never wedge the step loop."""
        did = False
        while True:
            try:
                tokens, kv, reply = self._kv_imports.get_nowait()
            except queue.Empty:
                return did
            did = True
            try:
                self._consult_phase_chaos("import")
                bs = self.blocks.block_size
                n = len(tokens) // bs
                blocks = self.blocks.reserve_import(n)
                if blocks is None:
                    reply.put((
                        False,
                        RequestFailedError(
                            f"kv import: pool cannot cover {n} blocks"
                        ),
                    ))
                    continue
                try:
                    # no ascontiguousarray: scatter_blocks's per-chunk
                    # packing copies handle non-contiguous views, and a
                    # whole-payload memcpy here would stall the standing
                    # decode batch — on the step thread, for the full
                    # payload size, on every import
                    self.runner.scatter_blocks(blocks, kv)
                except Exception as e:  # noqa: BLE001
                    self.blocks.abort_import(blocks)
                    reply.put((False, e))
                    continue
                self.blocks.commit_import(blocks, tokens)
                # covered tokens, not blocks indexed: duplicates of
                # already-indexed prefixes still serve acquire_prefix
                reply.put((True, n * bs))
            except Exception as e:  # noqa: BLE001
                reply.put((False, e))

    # -- cluster KV tier (PR 17) ------------------------------------------
    def _tier_spill(self, digest: bytes, blk: int, hits: int) -> bool:
        """Spill half of the block manager's ONE spill-vs-drop policy
        point — invoked under the manager lock at every indexed-block
        eviction, so it only GATHERS here (one block, device→host) and
        defers the heavy publish (shm write + daemon RPC) to
        :meth:`_drain_tier_spills` on the next step. Popular blocks
        (ever hit, or already tier-resident) spill; cold ones drop."""
        digest_hex = digest.hex()
        with self._tier_lock:
            if digest_hex in self._tier_adverts or digest_hex in self._tier_pending:
                return True  # already tier-resident/queued: content survives
        if hits <= 0:
            return False  # never reused since indexing: cold, drop
        try:
            kv = self.runner.gather_blocks([blk])
        except Exception:  # noqa: BLE001 — a failed gather is a drop
            return False
        self._tier_spill_pending.append((digest, kv))
        return True

    def _drain_tier_spills(self) -> None:
        if not self._tier_spill_pending:
            return
        pending, self._tier_spill_pending = self._tier_spill_pending, []
        for digest, kv in pending:
            self._tier_enqueue(digest, kv, "evict")

    def _tier_enqueue(self, digest: bytes, kv, trigger: str) -> None:
        """Hand one gathered block to the tier publisher thread. The
        step thread only ever pays a lock + queue put here; the shm
        write and daemon RPC happen off the token-emission path. A full
        queue DROPS the write-back — tier warmth is best-effort and
        must never backpressure decode."""
        digest_hex = digest.hex()
        with self._tier_lock:
            if digest_hex in self._tier_adverts:
                self._tier_adverts.move_to_end(digest_hex)
                return
            if digest_hex in self._tier_pending:
                return
            self._tier_pending.add(digest_hex)
        try:
            self._tier_pub_q.put_nowait((digest, kv, trigger))
        except queue.Full:
            with self._tier_lock:
                self._tier_pending.discard(digest_hex)

    def _tier_publish_loop(self) -> None:
        while not self._stop.is_set():
            try:
                digest, kv, trigger = self._tier_pub_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._tier_writeback(digest, kv, trigger)
            except Exception:  # noqa: BLE001 — publish is best-effort
                pass
            finally:
                with self._tier_lock:
                    self._tier_pending.discard(digest.hex())
                self._tier_pub_q.task_done()

    def _drain_tier_pub_queue_sync(self) -> None:
        """Publish everything still queued on the CALLER's thread —
        the migrate path needs residency guaranteed before it errors
        the streams, so it cannot leave work racing its own exit."""
        while True:
            try:
                digest, kv, trigger = self._tier_pub_q.get_nowait()
            except queue.Empty:
                return
            try:
                self._tier_writeback(digest, kv, trigger)
            except Exception:  # noqa: BLE001
                pass
            finally:
                with self._tier_lock:
                    self._tier_pending.discard(digest.hex())
                self._tier_pub_q.task_done()

    def flush_tier_writebacks(self, timeout_s: float = 10.0) -> bool:
        """Block until the deferred tier publisher is idle (queue empty
        and no publish in flight). Tests and the migrate path use this
        to turn the asynchronous write-back into a happens-before."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._tier_lock:
                idle = not self._tier_pending
            if idle and self._tier_pub_q.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return False

    def _tier_writeback(self, digest: bytes, kv, trigger: str) -> None:
        """Publish one block payload into the tier + advert it. Dedup
        by advert: a digest this replica already adverts just refreshes
        recency (idempotent republish would rewrite identical bytes).
        Runs on the tier publisher thread (or the step thread for the
        synchronous migrate flush) — advert mutations take _tier_lock,
        the publish RPC deliberately does not."""
        digest_hex = digest.hex()
        with self._tier_lock:
            if digest_hex in self._tier_adverts:
                self._tier_adverts.move_to_end(digest_hex)
                return
        from ray_tpu.inference import kv_transfer
        from ray_tpu.observability import rpc_metrics

        desc = kv_transfer.tier_publish(
            digest, kv, self.blocks.block_size, ns=self._tier_ns
        )
        if desc is None:
            return
        with self._tier_lock:
            self._tier_adverts[digest_hex] = desc
            self._tier_adverts.move_to_end(digest_hex)
            # advert cap: dropping the LRU advert retracts it from the
            # gossip (routers purge on the next report's advert-set diff) —
            # the daemon registry may keep the bytes until ITS ttl/cap
            cap = max(1, GLOBAL_CONFIG.kv_tier_max_adverts)
            while len(self._tier_adverts) > cap:
                self._tier_adverts.popitem(last=False)
        rpc_metrics.KV_TIER_PUBLISHES.inc(labels={"trigger": trigger})

    def _tier_writeback_full_blocks(
        self, req: Request, written, trigger: str, sync: bool = False
    ) -> None:
        """Write back every full block of ``written`` (token positions
        whose K/V is in the cache) that is not yet tier-resident. The
        per-block chain digests are recomputed from tokens — the same
        capability-name derivation any future reader uses. Gathers run
        HERE (the step thread: device cache reads must not race the
        loop's own swaps); the publish defers to the tier publisher
        thread unless ``sync`` (migrate needs residency-before-error)."""
        bs = self.blocks.block_size
        owned = self.blocks.owned(req.request_id)
        n_full = min(len(written) // bs, len(owned))
        prev = b""
        for i in range(n_full):
            prev = _chain_digest(prev, written[i * bs : (i + 1) * bs])
            with self._tier_lock:
                resident = (
                    prev.hex() in self._tier_adverts
                    or prev.hex() in self._tier_pending
                )
            if resident:
                continue
            try:
                kv = self.runner.gather_blocks([owned[i]])
            except Exception:  # noqa: BLE001 — write-back is best-effort
                return
            if sync:
                self._tier_writeback(prev, kv, trigger)
            else:
                self._tier_enqueue(prev, kv, trigger)

    def _migrate_inflight(self) -> None:
        """Drain-with-migration (consumer (a) of the tier): flush every
        in-flight request's written KV — prompt AND generated — into
        the tier, then fail it with :class:`KvMigrationHandoff` so
        the router resumes it on a survivor that faults the KV back in.
        The generated-token half is what plain disagg export never
        covered; it is exactly the state a mid-stream failover used to
        re-prefill via replay. Publishes run synchronously here: the
        handoff error must not reach the router before the blocks are
        tier-resident, or the survivor's fault-in races our exit."""
        self._migrate_on_drain = False
        self._drain_tier_pub_queue_sync()
        self.flush_tier_writebacks(5.0)
        for req in self.scheduler.take_all():
            try:
                # Only positions whose K/V truly reached the device
                # cache: blocks are allocated for the WHOLE prompt at
                # admission but chunked prefill writes incrementally —
                # a mid-prefill request has written exactly
                # effective_prompt[:prefill_pos] (a prefix of
                # prompt+generated), and decode has written through
                # context_len-1 once prefill is done. Publishing past
                # that point would advert never-written device blocks
                # under the VALID chain digest of the real tokens (the
                # CRC gate covers transport, not content) and poison
                # every future fault-in of that prefix.
                end = (
                    (req.context_len - 1) if req.prefill_done else req.prefill_pos
                )
                if end > 0:
                    written = (req.prompt + req.generated)[:end]
                    self._tier_writeback_full_blocks(
                        req, written, "migrate", sync=True
                    )
            except Exception:  # noqa: BLE001 — flush failure → plain replay
                pass
            self.blocks.free(req.request_id)
            req.state = FAILED
            self._finish_request(
                req, FAILED, error=KvMigrationHandoff(KV_MIGRATION_MARKER)
            )

    def _emit_token(self, req: Request, token: int) -> None:
        if req.finished:
            # cancelled/failed after this step's plan was built but before
            # its token was sampled: emitting would stream a stray token
            # and the done-path below would overwrite CANCELLED with
            # FINISHED, double-counting requests_total
            return
        req.generated.append(token)
        if self.engine_cfg.kv_tier_enabled:
            # tier write-back trigger 2: each DECODE block boundary —
            # position n_written-1's K/V was written by the step that
            # sampled this token, so when n_written crosses a block
            # boundary a new immutable full block exists. Flushing it
            # now is what makes a mid-stream SIGKILL recoverable by
            # fault-in: the generated prefix is already tier-resident.
            n_written = len(req.prompt) + len(req.generated) - 1
            if n_written > 0 and n_written % self.blocks.block_size == 0:
                self._tier_writeback_full_blocks(
                    req, (req.prompt + req.generated)[:n_written], "decode"
                )
        now = time.monotonic()
        self._token_times.append(now)
        self.metrics["tokens_total"].inc()
        first_span: Optional[tuple] = None
        ttft: Optional[float] = None
        with self._lock:
            q = self._out.get(req.request_id)
            if req.request_id not in self._first_token_at:
                self._first_token_at[req.request_id] = now
                sub = self._submitted_at.get(req.request_id)
                if sub is not None:
                    ttft = now - sub
                    self._ttft_tape.observe(ttft)
                    self._recent_ttfts.append((now, ttft))
                    wire = self._trace_ctx.get(req.request_id)
                    if wire is not None:
                        first_span = (wire, ttft)
        # SLO-ledger stamps: TTFT on the first token, the inter-token
        # gap on every later one (one histogram observe = bisect +
        # increment; the request object carries the per-token state)
        slo_labels = {
            "deployment": self.slo_deployment,
            "tenant_class": req.tenant_class,
        }
        if ttft is not None:
            if req.record_slo:
                self.metrics["ttft"].observe(ttft, labels=slo_labels)
        elif req.last_emit_at is not None:
            gap = now - req.last_emit_at
            self._recent_itls.append((now, gap))
            if gap > req.max_itl_s:
                req.max_itl_s = gap
            if req.record_slo:
                self.metrics["itl"].observe(gap, labels=slo_labels)
        req.last_emit_at = now
        if first_span is not None:
            # TTFT span under the caller's trace: engine admission +
            # queue + prefill chunks up to the first sampled token
            end_us = timeline._now_us()
            _tracing.record_span(
                first_span[0], "llm_first_token",
                end_us - first_span[1] * 1e6, end_us, category="inference",
                request_id=req.request_id,
                prompt_tokens=len(req.prompt),
                cached_prefix_tokens=req.cached_prefix_tokens,
            )
        if q is not None:
            q.put(token)
        done = (
            len(req.generated) >= req.max_new_tokens
            or (req.eos_token is not None and token == req.eos_token)
        )
        if done:
            # index the finished conversation's full blocks (multi-turn
            # reuse) BEFORE finish() releases them to the cache LRU.
            # Only positions whose K/V is actually written qualify: the
            # final sampled token's K/V never was (its decode step never
            # runs), so the registered prefix stops one token short.
            written = (req.prompt + req.generated)[: req.context_len - 1]
            self.blocks.register_prefix(req.request_id, written)
        if done and self.scheduler.finish(req, FINISHED):
            # finish() returns False when cancel() won the race after the
            # req.finished guard above — the cancel path already notified
            # the waiter and counted the outcome
            self._finish_request(req, FINISHED, error=None)

    def _finish_request(self, req: Request, state: str, error: Optional[Exception]) -> None:
        outcome = {FINISHED: "finished", CANCELLED: "cancelled"}.get(state, "failed")
        if self.spec is not None:
            try:
                self.spec.release(req.request_id)
            except Exception:  # noqa: BLE001 — draft cleanup is best-effort
                pass
        now = time.monotonic()
        with self._lock:
            q = self._out.get(req.request_id)
            submitted = self._submitted_at.pop(req.request_id, None)
            wire = self._trace_ctx.pop(req.request_id, None)
            first_token = self._first_token_at.pop(req.request_id, None)
            self._books[outcome] = self._books.get(outcome, 0) + 1
            if q is not None:
                # the queue stays for a late tokens() call; stamp it so an
                # abandoned stream is reaped instead of pinned forever
                self._finished_at[req.request_id] = now
        self._close_ledger(req, outcome, submitted, first_token, now, wire, error)
        if wire is not None and submitted is not None:
            # whole-request span under the caller's trace: admission
            # through the last decode step (covers every prefill chunk
            # and decode token the step loop ran for this request)
            end_us = timeline._now_us()
            _tracing.record_span(
                wire, "llm_request",
                end_us - (time.monotonic() - submitted) * 1e6, end_us,
                category="inference",
                request_id=req.request_id,
                outcome=outcome,
                generated_tokens=len(req.generated),
                preemptions=req.preemptions,
            )
        if q is not None:
            q.put(error if error is not None else _END)
        self.metrics["requests_total"].inc(labels={"outcome": outcome})

    def _close_ledger(
        self,
        req: Request,
        outcome: str,
        submitted: Optional[float],
        first_token: Optional[float],
        now: float,
        wire,
        error: Optional[Exception],
    ) -> None:
        """Close a request's SLO ledger: observe e2e, split its token
        work into goodput vs fault cost, and file the flight-recorder
        entry (flagged when the request violated an SLO target, was
        preempted, or ended abnormally — those are exactly the outliers
        an operator asks the recorder about)."""
        from ray_tpu.observability.slo import flight_recorder

        labels = {
            "deployment": self.slo_deployment,
            "tenant_class": req.tenant_class,
        }
        e2e = (now - submitted) if submitted is not None else None
        ttft = (
            first_token - submitted
            if submitted is not None and first_token is not None
            else None
        )
        if e2e is not None and req.record_slo:
            self.metrics["e2e"].observe(e2e, labels=labels)
        n_gen = len(req.generated)
        if n_gen:
            if outcome == "finished":
                self.metrics["goodput"].inc(n_gen, labels=labels)
            else:
                # decode work that never reached a satisfied client is
                # fault cost, attributed by why it was thrown away
                self.metrics["fault"].inc(
                    n_gen,
                    labels={"deployment": self.slo_deployment, "reason": outcome},
                )
        flags: List[str] = []
        if outcome != "finished":
            flags.append(outcome)
        if req.preemptions:
            flags.append("preempted")
        if ttft is not None and ttft > GLOBAL_CONFIG.slo_ttft_slow_s:
            flags.append("slow_ttft")
        if req.max_itl_s > GLOBAL_CONFIG.slo_itl_slow_s:
            flags.append("slow_itl")
        stages = {k: round(float(v), 6) for k, v in req.ledger_stages.items()}
        if submitted is not None and req.admitted_at is not None:
            stages["queue"] = round(max(0.0, req.admitted_at - submitted), 6)
        if req.admitted_at is not None and req.prefill_done_at is not None:
            stages["prefill"] = round(
                max(0.0, req.prefill_done_at - req.admitted_at), 6
            )
        if first_token is not None:
            stages["decode"] = round(max(0.0, now - first_token), 6)
        entry = {
            "tier": "engine",
            "request_id": req.request_id,
            "trace_id": wire[0] if wire else None,
            "deployment": self.slo_deployment,
            "tenant_class": req.tenant_class,
            "outcome": outcome,
            "error": repr(error) if error is not None else None,
            "ttft_s": round(ttft, 6) if ttft is not None else None,
            "e2e_s": round(e2e, 6) if e2e is not None else None,
            "max_itl_s": round(req.max_itl_s, 6),
            "prompt_tokens": len(req.prompt),
            "generated_tokens": n_gen,
            "cached_prefix_tokens": req.cached_prefix_tokens,
            "preemptions": req.preemptions,
            "stages": stages,
            "flags": flags,
        }
        # slowest-K keys on TOTAL latency (TTFT only when the request
        # never streamed): a fast-first-token request that then decoded
        # for minutes is exactly the outlier the heap must retain
        flight_recorder().add(
            entry,
            flagged=bool(flags),
            slow_key=e2e if e2e is not None else ttft,
        )

    def _fail_all(self, error: Exception) -> None:
        for req in self.scheduler.take_all():
            self.blocks.free(req.request_id)
            req.state = FAILED
            self._finish_request(req, FAILED, error=error)

    def _reap_abandoned_streams(self) -> None:
        ttl = self.engine_cfg.finished_stream_ttl_s
        if ttl <= 0:
            return
        now = time.monotonic()
        if now < self._next_stream_reap:
            return
        self._next_stream_reap = now + min(ttl, 10.0)
        with self._lock:
            dead = [r for r, t in self._finished_at.items() if now - t > ttl]
            for rid in dead:
                self._finished_at.pop(rid, None)
                self._out.pop(rid, None)

    def _tokens_per_s(self) -> float:
        # expired timestamps are dropped incrementally: the step loop calls
        # this via _update_gauges, and a full copy-and-filter of the 2048-cap
        # deque every step was measurable overhead at decode rates
        now = time.monotonic()
        tt = self._token_times
        while tt and now - tt[0] > 10.0:
            tt.popleft()
        if len(tt) < 2:
            return 0.0
        span = max(now - tt[0], 1e-6)
        return len(tt) / span

    @staticmethod
    def _recent_quantile(samples: deque, q: float, window_s: float = 30.0) -> float:
        """Quantile over the (ts, value) samples inside ``window_s`` —
        the sliding-window control signal the autopilot steers on. 0.0
        when the window is empty (callers treat that as "no signal").
        Reads a list() copy: the reporter thread computes this while the
        step thread appends."""
        now = time.monotonic()
        vals = sorted(v for ts, v in list(samples) if now - ts <= window_s)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, int(math.ceil(q * len(vals))) - 1))
        return vals[idx]

    def _prefill_tokens_per_s(self, window_s: float = 10.0) -> float:
        now = time.monotonic()
        entries = [(ts, n) for ts, n in list(self._prefill_token_times)
                   if now - ts <= window_s]
        if not entries:
            return 0.0
        span = max(now - entries[0][0], 1e-6)
        return sum(n for _ts, n in entries) / span

    def _ttft_quantiles(self) -> Dict[str, float]:
        """stats()/bench back-compat shape ({"p50", "p99"}), now derived
        from this engine's log-bucket TTFT tape instead of a sorted
        sample deque (the old deque fed quantile GAUGES, which cannot be
        aggregated across replicas — the histogram can)."""
        with self._lock:
            if self._ttft_tape.total == 0:
                return {}
            p50 = self._ttft_tape.quantile(0.50)
            p99 = self._ttft_tape.quantile(0.99)
        return {"p50": p50, "p99": p99}

    def _update_gauges(self, decode_batch: int) -> None:
        m = self.metrics
        m["decode_batch"].set(decode_batch)
        pre = self.scheduler.total_preempted - getattr(self, "_preempt_seen", 0)
        if pre > 0:
            m["preemptions_total"].inc(pre)
        self._preempt_seen = self.scheduler.total_preempted
        # fault-cost ledger: prefill tokens readmissions had to RE-RUN
        # (delta-tracked from the scheduler like the preemption counter)
        replay = self.scheduler.total_replay_prefill_tokens - self._replay_seen
        if replay > 0:
            m["fault"].inc(
                replay,
                labels={
                    "deployment": self.slo_deployment,
                    "reason": "preempt_replay",
                },
            )
            self._replay_seen = self.scheduler.total_replay_prefill_tokens
        # prefix-cache counters ride the same delta pattern (the manager
        # owns the source of truth; /metrics gets monotonic counters)
        for attr, name in (
            ("prefix_hits_total", "prefix_hits_total"),
            ("prefix_tokens_saved_total", "prefix_tokens_saved_total"),
            ("cow_copies_total", "cow_copies_total"),
        ):
            cur = getattr(self.blocks, attr)
            seen = self._prefix_seen.get(attr, 0)
            if cur > seen:
                m[name].inc(cur - seen)
                self._prefix_seen[attr] = cur
        # the remaining gauges cost lock round-trips — at hundreds of
        # steps/s that's pure step-loop overhead, so refresh them at 4 Hz
        # (first step always publishes, so metric names appear on
        # /metrics as soon as anything runs)
        now = time.monotonic()
        if now < self._next_gauge_refresh:
            return
        self._next_gauge_refresh = now + 0.25
        m["cache_util"].set(self.blocks.utilization())
        m["queue_depth"].set(self.scheduler.queue_depth())
        m["active"].set(len(self.scheduler.running))
        m["tps"].set(round(self._tokens_per_s(), 2))
        # adaptive speculative k rides the same 4 Hz refresh: steer the
        # live draft budget on the acceptance rate measured since the
        # last refresh window with enough proposals to mean something.
        # Shrinking/growing k never recompiles — the verify bucket stays
        # sized for speculative_k+1 and shorter windows pad via true_len.
        if self.spec is not None:
            prop, acc = self._spec_proposed, self._spec_accepted
            d_prop = prop - self._spec_window_seen[0]
            d_acc = acc - self._spec_window_seen[1]
            if d_prop >= 8:
                rate = d_acc / d_prop
                self._spec_acceptance = rate
                m["spec_acceptance"].set(round(rate, 4))
                self._spec_window_seen = (prop, acc)
                if self.engine_cfg.speculative_adaptive:
                    k = (
                        self.scheduler.spec_k_live
                        or self.engine_cfg.speculative_k
                    )
                    if rate < self.engine_cfg.speculative_accept_floor and k > 1:
                        self.scheduler.spec_k_live = k - 1
                    elif rate >= 0.75 and k < self.engine_cfg.speculative_k:
                        self.scheduler.spec_k_live = k + 1

    # -- introspection ----------------------------------------------------
    def set_deployment_name(self, name: str) -> None:
        """Stamp the serve deployment label onto this engine's SLO
        series (serve/replica.py calls this through the callable before
        any request arrives)."""
        self.slo_deployment = str(name or "")

    def ledger_books(self) -> Dict[str, Any]:
        """Intake conservation books (slo.books_balanced): submitted ==
        finished + failed + cancelled + queued + running, exactly, at
        quiesce — the gate that proves no fault path (chaos kill, drain
        cutoff, preemption churn, disconnect cancel) leaks a request."""
        with self._lock:
            books = dict(self._books)
        s = self.scheduler.stats()
        books.update(
            kind="engine",
            queued=s["queue_depth"],
            running=s["running"],
            total_admitted=s["total_admitted"],
            replay_prefill_tokens=self.scheduler.total_replay_prefill_tokens,
        )
        return books

    def slo_snapshot(self) -> Dict[str, Any]:
        """This process's SLO ledger state + this engine's books (the
        serve controller's ``slo_report`` fans this out per replica)."""
        from ray_tpu.observability import slo as _slo

        snap = _slo.snapshot()
        snap["books"] = self.ledger_books()
        snap["tier"] = "engine"
        snap["deployment"] = self.slo_deployment
        return snap

    def stats(self) -> Dict[str, Any]:
        # draft + verify buckets ride the same zero-recompile gate: a
        # speculative engine's compile books count the draft runner too
        spec_compiles = self.spec.compile_count() if self.spec is not None else 0
        spec_recompiles = (
            self.spec.recompiles_after_warmup() if self.spec is not None else 0
        )
        s = {
            "scheduler": self.scheduler.stats(),
            "blocks": self.blocks.stats(),
            "prefix_cache": self.blocks.prefix_stats(),
            "total_steps": self.total_steps,
            "draining": self._draining,
            "compile_count": self.runner.compile_count() + spec_compiles,
            "recompiles_after_warmup": (
                self.runner.recompiles_after_warmup() + spec_recompiles
            ),
            "tokens_per_s": round(self._tokens_per_s(), 2),
            "ttft": {k: round(v, 6) for k, v in self._ttft_quantiles().items()},
        }
        if self.spec is not None:
            prop, acc = self._spec_proposed, self._spec_accepted
            s["speculative"] = {
                "k": self.engine_cfg.speculative_k,
                "k_live": self.scheduler.spec_k_live,
                "draft": self.engine_cfg.speculative_draft,
                "proposed_tokens": prop,
                "accepted_tokens": acc,
                "rollbacks": self._spec_rollbacks,
                "acceptance_rate": round(acc / prop, 4) if prop else 0.0,
            }
        return s

    def routing_stats(self) -> Dict[str, Any]:
        """Compact replica load + cache-locality digest, gossiped to
        routers through the serve controller's long-poll channel
        (replica -> controller push -> router). Everything here must
        stay small and picklable — it travels on every routing-set
        update."""
        if self.engine_cfg.kv_tier_enabled:
            # snapshot under the tier lock: the step + publisher threads
            # move_to_end/popitem concurrently, and an unlocked dict()
            # copy can raise "OrderedDict mutated during iteration" and
            # fail the whole stats report
            with self._tier_lock:
                tier_adverts = dict(self._tier_adverts)
        else:
            tier_adverts = {}
        return {
            "queue_depth": self.scheduler.queue_depth(),
            "cache_util": round(self.blocks.utilization(), 4),
            "outstanding_tokens": self.scheduler.outstanding_tokens(),
            "block_size": self.blocks.block_size,
            "prefix_digest": self.blocks.prefix_digest(),
            # tier adverts ride the same gossip beat: digest hex ->
            # routable descriptor, bounded by kv_tier_max_adverts. A
            # digest absent from a holder's NEXT report is thereby
            # RETRACTED — routers diff per-actor advert sets and purge
            # in one hop instead of waiting out a TTL.
            "kv_tier": tier_adverts,
            "draining": self._draining,
            # queue-pressure export for the ingress tier: the admission
            # BOUND (so a proxy can judge fullness, not just depth) and
            # the monotonic intake count (so shed-vs-admitted reconciles
            # without a replica round-trip per request)
            "max_queue_depth": self.engine_cfg.max_queue_depth,
            "total_admitted": self.scheduler.total_admitted,
            # closed-loop control signals (serve/controller.py autopilot
            # + ingress ITL-derived shed threshold + disagg pool-ratio
            # adaptation): sliding-window latency quantiles and token
            # throughput, NOT the lifetime ledger tapes — the autopilot
            # must feel current burn, not the whole run's history
            "ttft_p99_s": round(self._recent_quantile(self._recent_ttfts, 0.99), 6),
            "itl_p99_s": round(self._recent_quantile(self._recent_itls, 0.99), 6),
            "decode_tokens_per_s": round(self._tokens_per_s(), 2),
            "prefill_tokens_per_s": round(self._prefill_tokens_per_s(), 2),
        }

    def healthy(self) -> bool:
        """Liveness the serve controller polls through ``replica.health()``:
        False once the step loop is dead, or wedged — work pending with
        no loop heartbeat inside ``step_stall_unhealthy_s``. A stalled
        step thread doesn't stop the actor's async loop from answering
        RPCs, so plain reachability checks can never catch it."""
        if self._stop.is_set():
            return False
        if self._thread is None or not self._thread.is_alive():
            return False
        stall = self.engine_cfg.step_stall_unhealthy_s
        if stall > 0 and self.scheduler.has_work():
            return time.monotonic() - self._last_beat <= stall
        return True

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no queued/running work remains (drain helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.scheduler.has_work():
                return True
            time.sleep(0.005)
        return not self.scheduler.has_work()
